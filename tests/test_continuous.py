"""Continuous batching + recurrent sessions (ISSUE 14): slot lifecycle
(join/leave determinism, generation-counter staleness protection, eviction
re-init), stateless continuous == microbatch bit-exactness end-to-end
through the gateway across two padding buckets, recurrent hidden-state
continuity across a household's request sequence, mid-flight hot-swap with
zero drops, the recurrent train -> export -> serve -> fleet chain, bursty
arrivals, the serve_continuous capture contract and the warehouse view.
Fast and JAX_PLATFORMS=cpu-safe by design (tier-1)."""

import json

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.models.ddpg_recurrent import (
    RecurrentActor,
    recurrent_ddpg_init,
)
from p2pmicrogrid_tpu.serve import (
    ContinuousBatcher,
    MicroBatchQueue,
    PolicyEngine,
    bursty_arrivals,
    export_policy_bundle,
    load_policy_bundle,
    serve_bench,
    serve_bench_continuous_compare,
)
from p2pmicrogrid_tpu.train import init_policy_state

A = 3  # community size for the stateless tests
AR = 2  # community size for the (heavier) recurrent tests


def _cfg(impl, n_agents=A):
    return default_config(
        sim=SimConfig(n_agents=n_agents),
        train=TrainConfig(implementation=impl),
        ddpg=DDPGConfig(buffer_size=16, batch_size=2),
    )


def _obs(n, n_agents=A, seed=0):
    rng = np.random.default_rng(seed)
    obs = np.empty((n, n_agents, 4), dtype=np.float32)
    obs[..., 0] = rng.uniform(0, 1, (n, n_agents))
    obs[..., 1:] = rng.uniform(-1, 1, (n, n_agents, 3))
    return obs


def _tabular_bundle(tmp_path, name="b", seed=0):
    cfg = _cfg("tabular")
    ps = init_policy_state(cfg, jax.random.PRNGKey(seed))
    ps = ps._replace(
        q_table=jax.random.normal(
            jax.random.PRNGKey(seed + 1), ps.q_table.shape
        )
    )
    return export_policy_bundle(cfg, ps, str(tmp_path / name)), cfg, ps


@pytest.fixture(scope="module")
def recurrent_bundle(tmp_path_factory):
    """One recurrent bundle + engine shared by the recurrent tests (the
    LSTM bucket compiles are the expensive part)."""
    cfg = _cfg("ddpg_recurrent", n_agents=AR)
    st = recurrent_ddpg_init(cfg.ddpg, jax.random.PRNGKey(0), seq_len=8)
    bundle = export_policy_bundle(
        cfg, st, str(tmp_path_factory.mktemp("rb") / "b")
    )
    engine = PolicyEngine(bundle_dir=bundle, max_batch=4, device="default")
    return bundle, engine


class TestRecurrentBundle:
    def test_manifest_records_hidden_state(self, recurrent_bundle):
        bundle, engine = recurrent_bundle
        manifest, _params = load_policy_bundle(bundle)
        hs = manifest["hidden_state"]
        assert hs["shape"] == [400]  # 4 carries x 100 lstm features
        assert hs["dtype"] == "float32"
        assert hs["init"] == "zeros"
        assert engine.is_recurrent and engine.hidden_dim == 400

    def test_act_threads_hidden_and_matches_full_sequence(
        self, recurrent_bundle
    ):
        _bundle, engine = recurrent_bundle
        _m, params = load_policy_bundle(_bundle)
        T = 3
        seq = _obs(T, n_agents=AR, seed=3)
        h = np.zeros((1, AR, 400), np.float32)
        acts = []
        for t in range(T):
            a, h = engine.act(seq[t][None], h)
            acts.append(a[0])
        # Reference: the full-sequence RecurrentActor over each agent's day.
        xs = np.transpose(seq, (1, 0, 2))  # [A, T, 4]
        ref = np.asarray(
            RecurrentActor().apply({"params": params}, xs)[..., 0]
        ).T  # [T, A]
        np.testing.assert_allclose(np.stack(acts), ref, atol=1e-6)

    def test_act_without_hidden_refused(self, recurrent_bundle):
        _bundle, engine = recurrent_bundle
        with pytest.raises(ValueError, match="hidden carry"):
            engine.act(_obs(1, n_agents=AR))

    def test_feedforward_refuses_hidden(self, tmp_path):
        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4)
        with pytest.raises(ValueError, match="feedforward"):
            engine.act(_obs(1), hidden=np.zeros((1, A, 4), np.float32))

    def test_microbatch_queue_refuses_recurrent(self, recurrent_bundle):
        _bundle, engine = recurrent_bundle
        with pytest.raises(ValueError, match="ContinuousBatcher"):
            MicroBatchQueue(engine)

    def test_sessions_off_refused_for_recurrent(self, recurrent_bundle):
        _bundle, engine = recurrent_bundle
        with pytest.raises(ValueError, match="sessions"):
            ContinuousBatcher(engine, sessions=False)

    def test_int8_export_refused(self):
        cfg = _cfg("ddpg_recurrent", n_agents=AR)
        st = recurrent_ddpg_init(cfg.ddpg, jax.random.PRNGKey(0), seq_len=8)
        with pytest.raises(ValueError, match="int8"):
            export_policy_bundle(cfg, st, "/tmp/never-written", dtype="int8")

    def test_sessions_carry_hidden_through_donated_step(
        self, recurrent_bundle
    ):
        _bundle, engine = recurrent_bundle
        sessions = engine.init_sessions(2)
        assert sessions.hidden.shape == (2, AR, 400)
        obs = _obs(2, n_agents=AR, seed=5)
        sessions, a1 = engine.step(sessions, obs)
        sessions, a2 = engine.step(sessions, obs)
        # Same obs, evolved carry: a recurrent policy must answer
        # differently — and the session hidden must be live.
        assert not np.array_equal(a1, a2)
        assert float(np.abs(np.asarray(sessions.hidden)).max()) > 0


class TestSlotLifecycle:
    def test_stateless_continuous_bit_exact_vs_direct(self, tmp_path):
        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        engine.warmup(include_step=False)
        obs = _obs(12, seed=7)
        want = engine.act(obs)
        with ContinuousBatcher(engine, max_slots=8) as cb:
            futs = [
                cb.submit(obs[i], household=f"h{i % 5}") for i in range(12)
            ]
            got = np.stack([f.result(timeout=30) for f in futs])
        np.testing.assert_array_equal(got, want)

    def test_two_bucket_coverage_bit_exact_manual_stepping(self, tmp_path):
        """Deterministic two-bucket proof (autostart=False removes worker
        timing): a 3-row step pads to bucket 4, a 1-row step hits bucket 1
        — two distinct compiled programs, both bit-exact vs direct act."""
        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        engine.warmup(include_step=False)
        obs = _obs(4, seed=43)
        want = engine.act(obs)
        base = dict(engine.stats)
        with ContinuousBatcher(
            engine, max_slots=8, autostart=False
        ) as cb:
            futs3 = [cb.submit(obs[i], household=f"h{i}") for i in range(3)]
            assert cb.step_once() == 3      # one step, bucket 4 (1 pad row)
            fut1 = cb.submit(obs[3], household="h3")
            assert cb.step_once() == 1      # one step, bucket 1 (no pad)
            for i, f in enumerate(futs3):
                np.testing.assert_array_equal(f.result(1), want[i])
            np.testing.assert_array_equal(fut1.result(1), want[3])
        assert engine.stats["batches"] - base["batches"] == 2
        assert engine.stats["padded_rows"] - base["padded_rows"] == 1
        assert engine.stats["rows"] - base["rows"] == 4

    def test_join_leave_determinism_under_interleaved_arrivals(
        self, tmp_path
    ):
        """The same interleaved arrival order twice -> identical answers,
        identical slot assignments, identical generations."""
        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        obs = _obs(16, seed=11)
        hh = [f"h{i % 3}" for i in range(16)]

        def run():
            engine = PolicyEngine(bundle_dir=bundle, max_batch=4)
            engine.warmup(include_step=False)
            with ContinuousBatcher(engine, max_slots=2) as cb:
                futs = [
                    cb.submit(obs[i], household=hh[i]) for i in range(16)
                ]
                got = np.stack([f.result(timeout=30) for f in futs])
                info = {
                    h: cb.session_info(h)
                    for h in set(hh)
                    if cb.session_info(h) is not None
                }
                stats = dict(cb.stats)
            return got, {
                h: (i["slot"], i["gen"], i["served"]) for h, i in info.items()
            }, stats

        got1, info1, stats1 = run()
        got2, info2, stats2 = run()
        np.testing.assert_array_equal(got1, got2)
        assert info1 == info2
        assert stats1["evictions"] == stats2["evictions"]
        assert stats1["joins"] == stats2["joins"]

    def test_generation_counter_protects_retired_row(self, recurrent_bundle):
        """A household whose slot was retired and reassigned must come back
        under a FRESH generation with a deterministic zero-carry re-init —
        never the new owner's (or its own stale) hidden state."""
        _bundle, engine = recurrent_bundle
        obs = _obs(4, n_agents=AR, seed=13)
        with ContinuousBatcher(engine, max_slots=1) as cb:
            a_first = cb.submit(obs[0], household="alice").result(30)
            info0 = cb.session_info("alice")
            assert cb.end_session("alice")
            # bob takes the (only) slot under a bumped generation.
            cb.submit(obs[1], household="bob").result(30)
            info_bob = cb.session_info("bob")
            assert info_bob["slot"] == info0["slot"]
            assert info_bob["gen"] > info0["gen"]
            # alice returns: bob has nothing queued, so the LRU evicts him
            # and alice re-inits deterministically — her answer equals a
            # fresh-carry answer, NOT a continuation of anyone's state.
            a_again = cb.submit(obs[0], household="alice").result(30)
            info1 = cb.session_info("alice")
        np.testing.assert_array_equal(a_again, a_first)
        assert info1["gen"] > info0["gen"]
        assert info1["served"] == 1

    def test_eviction_reinit_bit_exact(self, recurrent_bundle):
        """Two households thrash one slot: every request re-inits, and each
        answer is bit-exact with the fresh-carry engine reference."""
        _bundle, engine = recurrent_bundle
        obs = _obs(2, n_agents=AR, seed=17)
        want = [
            engine.act(obs[i][None], engine.init_hidden(1))[0][0]
            for i in range(2)
        ]
        with ContinuousBatcher(engine, max_slots=1) as cb:
            for round_ in range(2):
                for i, h in enumerate(("a", "b")):
                    got = cb.submit(obs[i], household=h).result(30)
                    np.testing.assert_array_equal(got, want[i])
            assert cb.stats["evictions"] >= 3

    def test_recurrent_continuity_across_request_sequence(
        self, recurrent_bundle
    ):
        """A household's interleaved request stream sees ONE continuous
        hidden trajectory — equal to a stateful engine replay — while other
        households' traffic shares the same steps."""
        _bundle, engine = recurrent_bundle
        T = 4
        seq = _obs(T, n_agents=AR, seed=19)
        noise = _obs(T, n_agents=AR, seed=23)
        with ContinuousBatcher(engine, max_slots=4) as cb:
            got = []
            for t in range(T):
                f_main = cb.submit(seq[t], household="main")
                f_other = cb.submit(noise[t], household=f"other-{t % 2}")
                got.append(f_main.result(30))
                f_other.result(30)
            info = cb.session_info("main")
        h = np.asarray(engine.init_hidden(1))
        want = []
        for t in range(T):
            a, h = engine.act(seq[t][None], h)
            want.append(a[0])
        np.testing.assert_allclose(np.stack(got), np.stack(want), atol=1e-6)
        assert info["served"] == T and info["gen"] == 0

    def test_same_household_requests_serialize_in_order(
        self, recurrent_bundle
    ):
        """Back-to-back requests of ONE household submitted before any step
        runs still step in submission order through consecutive steps."""
        _bundle, engine = recurrent_bundle
        T = 3
        seq = _obs(T, n_agents=AR, seed=29)
        with ContinuousBatcher(engine, max_slots=2) as cb:
            futs = [cb.submit(seq[t], household="hh") for t in range(T)]
            got = [f.result(30) for f in futs]
            assert cb.session_info("hh")["served"] == T
        h = np.asarray(engine.init_hidden(1))
        for t in range(T):
            a, h = engine.act(seq[t][None], h)
            np.testing.assert_allclose(got[t], a[0], atol=1e-6)

    def test_recurrent_slot_exhaustion_defers_never_scratches(
        self, recurrent_bundle
    ):
        """Under slot exhaustion a recurrent HOUSEHOLD request is deferred
        (FIFO kept, joins when a resident goes idle) — never silently
        answered from the scratch row's zero carry (manual stepping makes
        the contention deterministic)."""
        _bundle, engine = recurrent_bundle
        obs = _obs(2, n_agents=AR, seed=61)
        with ContinuousBatcher(
            engine, max_slots=1, autostart=False
        ) as cb:
            fa = cb.submit(obs[0], household="a")
            fb = cb.submit(obs[1], household="b")
            # Step 1: a takes the only slot; b (recurrent, slotless, a is
            # still pending at compose time) is DEFERRED, not scratched.
            assert cb.step_once() == 1
            assert cb.stats["slot_deferrals"] == 1
            assert cb.stats["scratch_rows"] == 0
            a1 = fa.result(1)
            assert not fb.done()
            # Step 2: a is idle now — evicted; b joins under a fresh slot.
            assert cb.step_once() == 1
            b1 = fb.result(1)
            assert cb.stats["evictions"] == 1
            assert cb.session_info("b")["served"] == 1
        # Both answers equal the fresh-carry reference (each household's
        # FIRST slot), proving neither was polluted by the other's state.
        want = engine.act(obs, np.asarray(engine.init_hidden(2)))[0]
        np.testing.assert_array_equal(a1, want[0])
        np.testing.assert_array_equal(b1, want[1])

    def test_slot_wait_timeout_fails_loudly_naming_the_fix(
        self, recurrent_bundle
    ):
        """A recurrent household that cannot get a slot does not starve
        invisibly: past slot_wait_timeout_s its request fails with an
        error naming --max-sessions."""
        _bundle, engine = recurrent_bundle
        obs = _obs(2, n_agents=AR, seed=67)
        with ContinuousBatcher(
            engine, max_slots=1, autostart=False, slot_wait_timeout_s=0.0
        ) as cb:
            fa = cb.submit(obs[0], household="a")
            fb = cb.submit(obs[1], household="b")
            # a takes the slot and stays "pending-busy" this compose;
            # b's wait (timeout 0) is already expired -> loud failure.
            assert cb.step_once() == 1
            fa.result(1)
            with pytest.raises(RuntimeError, match="max-sessions"):
                fb.result(1)
            assert cb.stats["slot_wait_expired"] == 1
            assert cb.depth == 0  # the expired request left the queue

    def test_cancelled_requests_are_pruned_not_stepped(
        self, recurrent_bundle
    ):
        """A cancelled request is dropped at compose time — it neither
        occupies the queue nor advances its household's hidden carry."""
        _bundle, engine = recurrent_bundle
        obs = _obs(2, n_agents=AR, seed=71)
        with ContinuousBatcher(
            engine, max_slots=2, autostart=False
        ) as cb:
            f1 = cb.submit(obs[0], household="h")
            f2 = cb.submit(obs[1], household="h")
            assert f2.cancel()
            assert cb.step_once() == 1  # only the live request steps
            f1.result(1)
            assert cb.stats["cancelled_drops"] == 1
            assert cb.depth == 0
            assert cb.session_info("h")["served"] == 1  # carry advanced once

    def test_stateless_household_burst_rides_one_step(self, tmp_path):
        """Stateless engines do NOT serialize a household's rows: a burst
        of K same-household requests composes into ONE step (actions
        depend only on the obs — K step latencies would buy nothing)."""
        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        engine.warmup(include_step=False)
        obs = _obs(3, seed=73)
        want = engine.act(obs)
        with ContinuousBatcher(
            engine, max_slots=4, autostart=False
        ) as cb:
            futs = [cb.submit(obs[i], household="same") for i in range(3)]
            assert cb.step_once() == 3  # one step, not three
            for i, f in enumerate(futs):
                np.testing.assert_array_equal(f.result(1), want[i])
            assert cb.session_info("same")["served"] == 3

    def test_anonymous_requests_serve_fresh_carry(self, recurrent_bundle):
        _bundle, engine = recurrent_bundle
        obs = _obs(1, n_agents=AR, seed=31)
        want = engine.act(obs, engine.init_hidden(1))[0]
        with ContinuousBatcher(engine, max_slots=2) as cb:
            a1 = cb.submit(obs[0]).result(30)
            a2 = cb.submit(obs[0]).result(30)
            assert cb.stats["scratch_rows"] == 2
        np.testing.assert_array_equal(a1, want[0])
        np.testing.assert_array_equal(a2, want[0])  # no carry, no drift

    def test_occupancy_and_slot_wait_histograms(self, tmp_path):
        from p2pmicrogrid_tpu.telemetry import Telemetry

        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        tel = Telemetry(run_id="t")
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4, telemetry=tel)
        engine.warmup(include_step=False)
        obs = _obs(6, seed=37)
        with ContinuousBatcher(engine, max_slots=4) as cb:
            futs = [cb.submit(obs[i], household=f"h{i}") for i in range(6)]
            for f in futs:
                f.result(30)
        s = tel.summary()
        assert s["histograms"]["serve.batch_occupancy"]["count"] >= 1
        assert s["histograms"]["serve.batch_occupancy"]["max"] <= 1.0
        assert s["histograms"]["serve.slot_wait_ms"]["count"] == 6
        assert s["counters"]["serve.steps"] >= 1


class TestGatewayContinuous:
    def _gateway(self, bundle, batching, max_batch=8):
        from p2pmicrogrid_tpu.serve import (
            AdmissionConfig,
            GatewayServer,
            build_gateway,
        )

        gateway = build_gateway(
            [bundle],
            max_batch=max_batch,
            admission=AdmissionConfig(max_queue_depth=4096),
            batching=batching,
        )
        server = GatewayServer(gateway)
        return gateway, server

    def test_stateless_gateway_bit_exact_vs_microbatch_two_buckets(
        self, tmp_path
    ):
        """Acceptance: the SAME requests through a microbatch gateway and a
        continuous gateway answer bit-identically, across two padding
        buckets, end-to-end over the wire."""
        import urllib.request

        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        # Mixed request sizes, sent sequentially (blocking): a 3-row
        # request executes as one 3-row step/batch (pads to bucket 4), a
        # 1-row request as bucket 1 — BOTH arms provably serve through two
        # distinct compiled bucket programs.
        sizes = [3, 1, 3, 1]
        obs = _obs(sum(sizes), seed=41)
        answers = {}
        for batching in ("micro", "continuous"):
            gateway, server = self._gateway(bundle, batching)
            try:
                host, port = server.start()
                got = []
                start = 0
                for i, size in enumerate(sizes):
                    rows = obs[start : start + size]
                    start += size
                    body = json.dumps({
                        "household": f"h{i}",
                        "obs": (
                            rows.tolist() if size > 1 else rows[0].tolist()
                        ),
                    }).encode()
                    req = urllib.request.Request(
                        f"http://{host}:{port}/v1/act", data=body,
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=30) as resp:
                        doc = json.loads(resp.read())
                    if size > 1:
                        got.extend(doc["actions"])
                    else:
                        got.append(doc["actions"])
                default = gateway.registry.get(gateway.registry.default_hash)
                stats = dict(default.engine.stats)
                answers[batching] = np.asarray(got, np.float32)
            finally:
                server.stop()
            assert stats["rows"] == sum(sizes)
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        want = engine.act(obs)
        np.testing.assert_array_equal(answers["micro"], want)
        np.testing.assert_array_equal(answers["continuous"], want)

    def test_hot_swap_mid_flight_zero_drops(self, tmp_path):
        """A default hot-swap while continuous traffic is in flight drops
        nothing: every request answers 200 from one of the two bundles."""
        import concurrent.futures
        import urllib.request

        bundle_a, _c, _p = _tabular_bundle(tmp_path, name="a", seed=0)
        cfg_b = default_config(
            sim=SimConfig(n_agents=A),
            train=TrainConfig(implementation="tabular", seed=43),
        )
        ps_b = init_policy_state(cfg_b, jax.random.PRNGKey(9))
        ps_b = ps_b._replace(
            q_table=jax.random.normal(
                jax.random.PRNGKey(10), ps_b.q_table.shape
            )
        )
        bundle_b = export_policy_bundle(cfg_b, ps_b, str(tmp_path / "bb"))
        from p2pmicrogrid_tpu.serve import (
            AdmissionConfig,
            GatewayServer,
            build_gateway,
        )

        gateway = build_gateway(
            [bundle_a, bundle_b],
            max_batch=8,
            admission=AdmissionConfig(max_queue_depth=4096),
            batching="continuous",
        )
        server = GatewayServer(gateway)
        obs = _obs(40, seed=47)
        hashes = set(gateway.registry.hashes)

        def one(i):
            body = json.dumps({
                "household": f"h{i % 6}", "obs": obs[i].tolist(),
            }).encode()
            req = urllib.request.Request(
                f"http://{gateway.host}:{gateway.port}/v1/act", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())

        try:
            server.start()
            other = [
                h for h in gateway.registry.hashes
                if h != gateway.registry.default_hash
            ][0]
            with concurrent.futures.ThreadPoolExecutor(8) as pool:
                futs = [pool.submit(one, i) for i in range(20)]
                swap_body = json.dumps({"config_hash": other}).encode()
                swap_req = urllib.request.Request(
                    f"http://{gateway.host}:{gateway.port}/admin/swap",
                    data=swap_body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(swap_req, timeout=30) as resp:
                    assert resp.status == 200
                futs += [pool.submit(one, i) for i in range(20, 40)]
                docs = [f.result(timeout=60) for f in futs]
        finally:
            server.stop()
        assert len(docs) == 40  # zero drops: every request answered
        served = {d["config_hash"] for d in docs}
        assert served <= hashes
        # Traffic actually moved to the swapped-in default.
        assert other in {d["config_hash"] for d in docs[20:]}


class TestRecurrentFleet:
    def test_recurrent_bundle_serves_through_fleet(self, recurrent_bundle):
        """Acceptance: a recurrent bundle serves through the fleet tier
        (router + replicas + household affinity) in a serve-bench --fleet
        style run with availability 1.0; the bit-exact comparator is
        omitted (stateless replay is not a valid reference for a stateful
        policy — continuity is asserted in TestSlotLifecycle)."""
        bundle, _engine = recurrent_bundle
        from p2pmicrogrid_tpu.serve import (
            AdmissionConfig,
            FleetRouter,
            LocalFleet,
            serve_bench_fleet,
        )

        fleet = LocalFleet(
            [bundle],
            n_replicas=2,
            max_batch=4,
            admission=AdmissionConfig(max_queue_depth=4096),
            batching="continuous",
            max_slots=16,
        )
        fleet.start()
        rows = []
        try:
            router = FleetRouter(fleet.replicas)
            serve_bench_fleet(
                router,
                n_agents=AR,
                reference_engine=None,
                rate_hz=400.0,
                n_requests=48,
                n_households=6,
                seed=0,
                burst_factor=4.0,
                burst_dwell_s=0.05,
                probe_interval_s=0.05,
                emit=rows.append,
            )
        finally:
            fleet.stop_all()
        head = rows[-1]
        assert head["metric"] == "serve_bench_fleet"
        assert head["availability"] == 1.0
        assert head["bit_exact"] is None
        assert head["n_requests"] == 48
        # The bursty knobs reach the fleet schedule and its headline too.
        assert head["burst_config"]["mode"] == "bursty"
        assert head["burst_config"]["burst_factor"] == 4.0


class TestRecurrentTrainChain:
    def test_train_export_serve_deterministic(self, tmp_path):
        """The full recurrent chain: train (day-granular, real physics) ->
        checkpoint -> export-bundle -> engine — deterministic under the
        seed, and the served greedy action matches the trained actor."""
        from p2pmicrogrid_tpu.train.recurrent import (
            recurrent_checkpoint_dir,
            save_recurrent_checkpoint,
            train_recurrent_community,
        )

        cfg = _cfg("ddpg_recurrent", n_agents=AR)
        res1 = train_recurrent_community(
            cfg, episodes=2, key=jax.random.PRNGKey(3)
        )
        res2 = train_recurrent_community(
            cfg, episodes=2, key=jax.random.PRNGKey(3)
        )
        np.testing.assert_array_equal(res1.day_rewards, res2.day_rewards)
        jax.tree_util.tree_map(
            np.testing.assert_array_equal, res1.state.actor, res2.state.actor
        )

        model_dir = str(tmp_path / "models")
        save_recurrent_checkpoint(model_dir, cfg, res1.state, episode=2)
        from p2pmicrogrid_tpu.serve import export_bundle_from_checkpoint

        bundle = export_bundle_from_checkpoint(
            cfg,
            recurrent_checkpoint_dir(model_dir, cfg.setting),
            str(tmp_path / "bundle"),
        )
        manifest, params = load_policy_bundle(bundle)
        assert manifest["implementation"] == "ddpg_recurrent"
        assert manifest["hidden_state"]["shape"] == [400]
        engine = PolicyEngine(bundle_dir=bundle, max_batch=2,
                              device="default")
        obs = _obs(1, n_agents=AR, seed=53)
        a, h = engine.act(obs, engine.init_hidden(1))
        ref = np.asarray(
            RecurrentActor().apply(
                {"params": params}, obs[0][:, None, :]
            )[..., 0, 0]
        )
        np.testing.assert_allclose(a[0], ref, atol=1e-6)
        assert float(np.abs(h).max()) > 0


class TestBurstyLoadgen:
    def test_bursty_arrivals_deterministic(self):
        a = bursty_arrivals(200.0, 100, burst_factor=8.0, seed=5)
        b = bursty_arrivals(200.0, 100, burst_factor=8.0, seed=5)
        np.testing.assert_array_equal(a, b)
        assert (np.diff(a) > 0).all()
        c = bursty_arrivals(200.0, 100, burst_factor=8.0, seed=6)
        assert not np.array_equal(a, c)

    def test_burst_factor_one_is_poisson(self):
        from p2pmicrogrid_tpu.serve import poisson_arrivals

        np.testing.assert_array_equal(
            bursty_arrivals(100.0, 50, burst_factor=1.0, seed=7),
            poisson_arrivals(100.0, 50, seed=7),
        )

    def test_out_of_range_burst_factor_refused_loudly(self):
        from p2pmicrogrid_tpu.serve import make_arrivals

        # Routed through bursty_arrivals' validation — never silently
        # benched as plain Poisson.
        with pytest.raises(ValueError, match="burst_factor"):
            make_arrivals(100.0, 10, burst_factor=0.5)

    def test_bursty_mean_rate_preserved(self):
        a = bursty_arrivals(
            500.0, 4000, burst_factor=8.0, burst_dwell_s=0.1, seed=0
        )
        rate = len(a) / a[-1]
        assert 350.0 < rate < 700.0  # mean-preserving construction

    def test_bursty_is_burstier_than_poisson(self):
        from p2pmicrogrid_tpu.serve import poisson_arrivals

        b = bursty_arrivals(
            500.0, 4000, burst_factor=10.0, burst_dwell_s=0.2, seed=1
        )
        p = poisson_arrivals(500.0, 4000, seed=1)
        # Dispersion of per-window counts: MMPP must exceed Poisson.
        def dispersion(arr):
            counts = np.histogram(
                arr, bins=np.arange(0.0, arr[-1], 0.1)
            )[0]
            return counts.var() / counts.mean()

        assert dispersion(b) > 2.0 * dispersion(p)

    def test_serve_bench_headline_reports_burst_config(self, tmp_path):
        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4)
        rows = serve_bench(
            engine, rate_hz=5000.0, n_requests=64, max_batch=4,
            max_wait_s=0.001, seed=3, burst_factor=6.0, burst_dwell_s=0.05,
        )
        bc = rows[-1]["burst_config"]
        assert bc["mode"] == "bursty"
        assert bc["burst_factor"] == 6.0
        rows_plain = serve_bench(
            engine, rate_hz=5000.0, n_requests=64, max_batch=4,
            max_wait_s=0.001, seed=3,
        )
        assert rows_plain[-1]["burst_config"]["mode"] == "poisson"


class TestContinuousCompare:
    def test_compare_rows_and_schema(self, tmp_path):
        """The serve_continuous capture contract: headline last, both
        arms' percentiles, occupancy/slot-wait stats, bit-exact verdict,
        burst_config — and the schema checker accepts the written file."""
        import importlib.util
        import os

        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        rows = serve_bench_continuous_compare(
            bundle, rate_hz=600.0, n_requests=96, n_households=8,
            seed=0, burst_factor=6.0, burst_dwell_s=0.1,
            max_batch=8, max_wait_s=0.003,
        )
        head = rows[-1]
        assert head["metric"] == "serve_continuous"
        assert head["bit_exact_stateless"] is True
        assert head["n_compared"] > 0
        for key in ("p50_ms", "p95_ms", "p99_ms", "micro_p99_ms",
                    "vs_microbatch", "occupancy_mean", "occupancy_p95",
                    "slot_wait_p50_ms", "slot_wait_p95_ms"):
            assert isinstance(head[key], (int, float))
        assert head["burst_config"]["mode"] == "bursty"
        assert head["transport"] == "mux"

        capture = tmp_path / "SERVE_CB_test.jsonl"
        capture.write_text(
            "".join(json.dumps(r) + "\n" for r in rows)
        )
        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "check_artifacts_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        problems: list = []
        mod.check_serve_cb_jsonl(str(capture), problems)
        assert problems == []
        # A headline stripped of its verdict is caught.
        bad = dict(head)
        del bad["bit_exact_stateless"]
        capture.write_text(
            "".join(json.dumps(r) + "\n" for r in rows[:-1] + [bad])
        )
        problems = []
        mod.check_serve_cb_jsonl(str(capture), problems)
        assert any("bit_exact_stateless" in p for p in problems)

    def test_compare_refuses_recurrent(self, recurrent_bundle):
        bundle, _engine = recurrent_bundle
        with pytest.raises(ValueError, match="stateless"):
            serve_bench_continuous_compare(bundle, n_requests=4)

    def test_committed_capture_validates(self):
        import importlib.util
        import os

        path = os.path.join(
            os.path.dirname(__file__), "..", "artifacts",
            "SERVE_CB_r14.jsonl",
        )
        if not os.path.exists(path):
            pytest.skip("no committed SERVE_CB capture")
        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "check_artifacts_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        problems: list = []
        mod.check_serve_cb_jsonl(path, problems)
        assert problems == []
        rows = [
            json.loads(l) for l in open(path) if l.strip()
        ]
        head = rows[-1]
        # The acceptance bar: continuous p99 strictly better than the
        # microbatch p99 under the committed bursty profile, bit-exact.
        assert head["vs_microbatch"] > 1.0
        assert head["bit_exact_stateless"] is True
        assert head["burst_config"]["mode"] == "bursty"


class TestContinuousWarehouse:
    def test_continuous_view_joins_occupancy_and_traces(self, tmp_path):
        """serve.batch_occupancy / serve.slot_wait_ms histograms + the
        serve_request traces land in the warehouse attributable by
        (config_hash, batching) — the telemetry-query --continuous view."""
        from p2pmicrogrid_tpu.data.results import ResultsStore
        from p2pmicrogrid_tpu.serve import build_registry

        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        db = str(tmp_path / "r.db")
        obs = _obs(8, seed=59)
        for batching in ("micro", "continuous"):
            registry = build_registry(
                [bundle], max_batch=4, results_db=db, batching=batching,
                run_name=f"cb-{batching}",
            )
            try:
                b = registry.get(registry.default_hash)
                futs = [
                    b.queue.submit(obs[i], household=f"h{i % 3}")
                    for i in range(8)
                ]
                for f in futs:
                    f.result(timeout=30)
            finally:
                registry.close_all()
        with ResultsStore(db) as store:
            rows = store.query_continuous_view()
        by_batching = {r["batching"]: r for r in rows}
        assert set(by_batching) == {"micro", "continuous"}
        cont = by_batching["continuous"]
        assert cont["n_requests"] == 8
        assert 0.0 < cont["occupancy_mean"] <= 1.0
        assert cont["slot_wait_p95_ms"] is not None
        assert by_batching["micro"]["n_requests"] == 8
        assert by_batching["micro"]["occupancy_mean"] is None

    def test_telemetry_query_continuous_cli(self, tmp_path, capsys):
        from p2pmicrogrid_tpu.cli import main
        from p2pmicrogrid_tpu.serve import build_registry

        bundle, _cfg_, _ps = _tabular_bundle(tmp_path)
        db = str(tmp_path / "r.db")
        registry = build_registry(
            [bundle], max_batch=4, results_db=db, batching="continuous",
        )
        try:
            b = registry.get(registry.default_hash)
            b.queue.submit(_obs(1)[0], household="h0").result(timeout=30)
        finally:
            registry.close_all()
        rc = main(["telemetry-query", "--results-db", db, "--continuous"])
        assert rc == 0
        out = capsys.readouterr().out
        rows = [json.loads(l) for l in out.splitlines() if l.strip()]
        assert any(r.get("batching") == "continuous" for r in rows)
        # --watch combination refused like the other views.
        rc = main([
            "telemetry-query", "--results-db", db, "--continuous", "--watch",
        ])
        assert rc == 2
