"""Autopilot: crash-safe unattended continual-deployment cycles (ISSUE 11).

Tier-1 acceptance: the cycle journal survives torn writes (digest-verified
atomic rename), the export/retention handshake coordinates compaction and
trace export by lease instead of racing (a forced race still fails loud),
settlement rows attribute training reward from billed outcomes with a
LOUD fallback, the canary's latency guard judges by server-side
serve_request spans (a slow arm cannot hide behind a fast loadgen clock),
dynamic bundle registration pushes a continual candidate into live
gateways, unattended cycles over an in-process fleet promote the honest
candidate and block the crafted regressions with availability 1.0, and a
real SIGKILL of the autopilot mid-retrain / mid-canary recovers from the
journal with the incumbent serving bit-exact. JAX_PLATFORMS=cpu-safe.
"""

import io
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.data.results import (
    ResultsStore,
    acquire_export_lease,
    last_export_watermark,
    release_export_lease,
)
from p2pmicrogrid_tpu.data.trace_export import (
    TracesCompactedError,
    bill_decisions,
    export_serve_traces,
    settlement_reward_fn,
)
from p2pmicrogrid_tpu.serve.autopilot import (
    Autopilot,
    AutopilotState,
    JournalCorrupt,
    journal_path,
    parse_inject_plan,
    read_journal,
    write_journal,
)
from p2pmicrogrid_tpu.serve.loadgen import synthetic_obs
from p2pmicrogrid_tpu.serve.promotion import make_crafted_bundle

A = 3


def _cfg(seed=0):
    return default_config(
        sim=SimConfig(n_agents=A),
        train=TrainConfig(implementation="tabular", seed=seed),
    )


# -- journal -------------------------------------------------------------------


class TestJournal:
    def test_round_trip(self, tmp_path):
        state = AutopilotState(
            cycle=3, phase="gating", incumbent_hash="inc",
            candidate_hash="cand", promotions=2,
            lineage=[{"cycle": 0, "incumbent": "a", "candidate": "inc",
                      "ts": 1.0}],
        )
        write_journal(str(tmp_path), state)
        back = read_journal(str(tmp_path))
        assert back.cycle == 3 and back.phase == "gating"
        assert back.lineage[0]["candidate"] == "inc"
        # No temp litter after a successful atomic write.
        leftovers = [f for f in os.listdir(tmp_path) if ".tmp" in f]
        assert leftovers == []

    def test_missing_reads_none(self, tmp_path):
        assert read_journal(str(tmp_path)) is None

    def test_corrupt_digest_fails_loud(self, tmp_path):
        write_journal(str(tmp_path), AutopilotState(cycle=1))
        path = journal_path(str(tmp_path))
        record = json.load(open(path))
        record["state"]["cycle"] = 99  # tamper without re-digesting
        json.dump(record, open(path, "w"))
        with pytest.raises(JournalCorrupt, match="digest"):
            read_journal(str(tmp_path))

    def test_torn_write_fails_loud(self, tmp_path):
        write_journal(str(tmp_path), AutopilotState(cycle=1))
        path = journal_path(str(tmp_path))
        raw = open(path).read()
        open(path, "w").write(raw[: len(raw) // 2])
        with pytest.raises(JournalCorrupt, match="unreadable"):
            read_journal(str(tmp_path))

    def test_unknown_phase_fails_loud(self, tmp_path):
        state = AutopilotState(cycle=0)
        state.phase = "warp-drive"
        write_journal(str(tmp_path), state)
        with pytest.raises(JournalCorrupt, match="phase"):
            read_journal(str(tmp_path))

    def test_parse_inject_plan(self):
        plan = parse_inject_plan("0:good, 2:nan_poisoned,3:continual")
        assert plan == {0: "good", 2: "nan_poisoned", 3: None}
        assert parse_inject_plan(None) == {}
        with pytest.raises(ValueError, match="unknown inject kind"):
            parse_inject_plan("0:sabotage")


# -- export/retention handshake ------------------------------------------------


def _seed_decisions(db, n=8, household="h1", hash_="hash-1", t0=1000.0):
    """A serve-role run with n pairable decisions at 1s spacing."""
    store = ResultsStore(db)
    store.con.execute(
        "INSERT OR REPLACE INTO telemetry_runs VALUES "
        "(?,?,?,?,?,?,?,?,?,?,?,?)",
        ("run-1", None, hash_, None, None, None, None, None, None,
         None, None, json.dumps({"serve_role": "default"})),
    )
    obs = synthetic_obs(n, A, seed=1)
    rows = [
        ("run-1", seq, t0 + seq, "serve_decision", None, None,
         json.dumps({"obs": obs[seq].tolist(), "action": [0.5] * A,
                     "household": household, "row": 0}))
        for seq in range(n)
    ]
    store.con.executemany(
        "INSERT INTO telemetry_points VALUES (?,?,?,?,?,?,?)", rows
    )
    store.con.commit()
    store.close()
    return obs


class TestExportHandshake:
    def test_active_lease_caps_compaction(self, tmp_path):
        db = str(tmp_path / "wh.db")
        t0 = time.time() - 100.0  # real-clock anchored: the lease TTL and
        _seed_decisions(db, n=8, t0=t0)  # the cutoff both use now()
        store = ResultsStore(db)
        lease = acquire_export_lease(
            store.con, "autopilot", window_start_ts=t0 + 4.0, ttl_s=600,
            config_hash="hash-1",
        )
        # Retention wants everything older than now gone — the lease
        # caps the cutoff at its window start instead.
        out = store.compact_serve_telemetry(older_than_hours=0.0)
        assert out["lease_capped"] is True
        (left,) = store.con.execute(
            "SELECT COUNT(*) FROM telemetry_points "
            "WHERE kind='serve_decision'"
        ).fetchone()
        assert left == 4  # ts t0+4..t0+7 survived
        # The export window (>= t0+4) is intact: no overlap, no refusal.
        ds = export_serve_traces(db, cfg=_cfg(), since_ts=t0 + 4.0)
        assert ds.n_transitions == 3
        release_export_lease(store.con, lease, exported_through_ts=t0 + 7.0)
        assert last_export_watermark(store.con, "hash-1") == pytest.approx(
            t0 + 7.0
        )
        # A decision served in the GAP after the release: retention must
        # not overtake the released watermark while unexported work
        # exists past it.
        store.con.execute(
            "INSERT INTO telemetry_points VALUES (?,?,?,?,?,?,?)",
            ("run-1", 99, t0 + 20.0, "serve_decision", None, None,
             json.dumps({"obs": [[0.0] * 4] * A, "action": [0.5] * A,
                         "household": "h1", "row": 0})),
        )
        store.con.commit()
        out = store.compact_serve_telemetry(older_than_hours=0.0)
        assert out["lease_capped"] is True
        assert out["cutoff_ts"] == pytest.approx(t0 + 7.0, abs=0.01)
        (left,) = store.con.execute(
            "SELECT COUNT(*) FROM telemetry_points "
            "WHERE kind='serve_decision'"
        ).fetchone()
        assert left == 2  # the frontier decision + the gap decision
        # The next cycle's export advances the frontier past the gap
        # decision, so retention follows it.
        lease2 = acquire_export_lease(
            store.con, "autopilot", window_start_ts=t0 + 7.0, ttl_s=600,
            config_hash="hash-1",
        )
        release_export_lease(store.con, lease2, exported_through_ts=t0 + 21.0)
        out = store.compact_serve_telemetry(older_than_hours=0.0)
        assert out["cutoff_ts"] == pytest.approx(t0 + 21.0, abs=0.01)
        (left,) = store.con.execute(
            "SELECT COUNT(*) FROM telemetry_points "
            "WHERE kind='serve_decision'"
        ).fetchone()
        assert left == 0
        # Retirement: a config that stops exporting stops gating one
        # lease TTL after its last release — the frontier must never pin
        # retention forever (simulated by aging the leases past expiry).
        store.con.execute(
            "UPDATE export_leases SET expires_ts = ?", (time.time() - 1,)
        )
        store.con.commit()
        out = store.compact_serve_telemetry(older_than_hours=0.0)
        assert out["lease_capped"] is False
        store.close()

    def test_expired_lease_stops_gating(self, tmp_path):
        db = str(tmp_path / "wh.db")
        _seed_decisions(db, n=4, t0=1000.0)
        store = ResultsStore(db)
        acquire_export_lease(
            store.con, "crashed-autopilot", window_start_ts=1000.0,
            ttl_s=1.0, now=1000.0,
        )
        # Long past the TTL: the crashed holder's lease must not block
        # retention forever.
        out = store.compact_serve_telemetry(older_than_hours=0.0)
        assert out["lease_capped"] is False
        assert out["decisions_compacted"] == 4
        store.close()

    def test_cancelled_lease_stops_gating_immediately(self, tmp_path):
        """A FAILED export cancels its lease outright (no fake watermark,
        no TTL wait) — retention resumes on the next pass."""
        from p2pmicrogrid_tpu.data.results import cancel_export_lease

        db = str(tmp_path / "wh.db")
        t0 = time.time() - 100.0
        _seed_decisions(db, n=4, t0=t0)
        store = ResultsStore(db)
        lease = acquire_export_lease(
            store.con, "doomed", window_start_ts=t0, ttl_s=600
        )
        assert store.compact_serve_telemetry(
            older_than_hours=0.0
        )["lease_capped"] is True
        cancel_export_lease(store.con, lease)
        out = store.compact_serve_telemetry(older_than_hours=0.0)
        assert out["lease_capped"] is False
        assert out["decisions_compacted"] == 4
        # No watermark was fabricated by the cancel.
        assert last_export_watermark(store.con, None) is None
        store.close()

    def test_forced_race_still_fails_loud(self, tmp_path):
        """Compaction into the export window (no lease / ignored lease)
        must still raise TracesCompactedError — the backstop contract."""
        db = str(tmp_path / "wh.db")
        _seed_decisions(db, n=8, t0=1000.0)
        store = ResultsStore(db)
        # Aggregate marker overlapping the window (ts_max inside it).
        store.con.execute(
            "INSERT INTO telemetry_points VALUES (?,?,?,?,?,?,?)",
            ("run-1", 1 << 41, 1006.0, "serve_request_agg", "bucket_1",
             4.0, json.dumps({"bucket": 1, "ts_min": 1000.0,
                              "ts_max": 1006.0})),
        )
        store.con.commit()
        store.close()
        with pytest.raises(TracesCompactedError, match="export lease"):
            export_serve_traces(db, cfg=_cfg(), since_ts=1004.0)

    def test_window_scoped_refusal_boundary(self, tmp_path):
        db = str(tmp_path / "wh.db")
        _seed_decisions(db, n=8, t0=1000.0)
        store = ResultsStore(db)
        store.con.execute(
            "INSERT INTO telemetry_points VALUES (?,?,?,?,?,?,?)",
            ("run-1", 1 << 41, 1003.0, "serve_request_agg", "bucket_1",
             4.0, json.dumps({"bucket": 1, "ts_min": 1000.0,
                              "ts_max": 1003.0})),
        )
        store.con.commit()
        store.close()
        # Window starts past the compacted tail: scheduled, not a race.
        ds = export_serve_traces(db, cfg=_cfg(), since_ts=1004.0)
        assert ds.n_transitions == 3
        # Unwindowed export still refuses (pre-handshake contract).
        with pytest.raises(TracesCompactedError):
            export_serve_traces(db, cfg=_cfg())


# -- metered settlement --------------------------------------------------------


class TestSettlement:
    def test_billed_rows_attribute_reward(self, tmp_path):
        db = str(tmp_path / "wh.db")
        _seed_decisions(db, n=6, t0=1000.0)
        cfg = _cfg()
        # A meter that bills DOUBLE: the joined reward must reflect the
        # bill, not the env model — that difference is the whole point.
        billed = bill_decisions(
            db, cfg, bill_fn=lambda obs, act: np.full(A, 2.0, np.float32)
        )
        assert billed == 6
        warn = io.StringIO()
        ds = export_serve_traces(
            db, cfg=cfg,
            reward_fn=settlement_reward_fn(db, cfg, warn_stream=warn),
        )
        assert ds.n_transitions == 5
        assert "settlement WARNING" not in warn.getvalue()
        from p2pmicrogrid_tpu.ops.thermal import comfort_penalty

        t_in = ds.obs[..., 1] * cfg.thermal.margin + cfg.thermal.setpoint
        want = -(2.0 + 10.0 * np.asarray(comfort_penalty(cfg.thermal, t_in)))
        np.testing.assert_allclose(ds.reward, want, rtol=1e-5)

    def test_missing_rows_fall_back_loud(self, tmp_path):
        from p2pmicrogrid_tpu.data.trace_export import trace_reward

        db = str(tmp_path / "wh.db")
        _seed_decisions(db, n=6, t0=1000.0)
        cfg = _cfg()
        warn = io.StringIO()
        ds = export_serve_traces(
            db, cfg=cfg,
            reward_fn=settlement_reward_fn(db, cfg, warn_stream=warn),
        )
        # No settlement rows at all: EVERY transition falls back, and the
        # warning says so — never silent.
        assert "settlement WARNING: 5/5" in warn.getvalue()
        np.testing.assert_allclose(
            ds.reward, trace_reward(cfg, ds.obs, ds.action), rtol=1e-6
        )


# -- server-side SLO attribution -----------------------------------------------


class _RegistryStub:
    """The minimal registry surface a controller with explicit routing
    hooks still touches."""

    default_hash = "inc"
    split = None

    def set_split(self, *a):
        pass

    def clear_split(self):
        pass

    def clear_pins(self):
        pass

    def swap(self, *a):
        pass


class TestServerSideSLO:
    def _warehouse_with_spans(self, db, hash_, latencies, since=100.0):
        store = ResultsStore(db)
        store.con.execute(
            "INSERT OR REPLACE INTO telemetry_runs VALUES "
            "(?,?,?,?,?,?,?,?,?,?,?,?)",
            (f"run-{hash_}", None, hash_, None, None, None, None, None,
             None, None, None, json.dumps({"serve_role": "default"})),
        )
        rows = [
            (f"run-{hash_}", i, since + 1.0 + i, "serve_request", None,
             None, json.dumps({"latency_ms": lat}))
            for i, lat in enumerate(latencies)
        ]
        store.con.executemany(
            "INSERT INTO telemetry_points VALUES (?,?,?,?,?,?,?)", rows
        )
        store.con.commit()
        store.close()

    def test_slow_arm_cannot_hide_behind_fast_client_clock(self, tmp_path):
        from p2pmicrogrid_tpu.serve.promotion import (
            CanaryBudgets,
            CanaryController,
            StagePlan,
            StageTraffic,
        )

        db = str(tmp_path / "wh.db")
        self._warehouse_with_spans(db, "cand", [900.0] * 16)
        self._warehouse_with_spans(db, "inc", [1.0] * 16)
        _Reg = _RegistryStub

        controller = CanaryController(
            _Reg(), candidate_hash="cand", incumbent_hash="inc",
            stages=(100.0,),
            budgets=CanaryBudgets(slo_p95_ms=500.0, min_requests=4),
            results_db=db,
        )
        n = 8
        # The CLIENT saw nothing wrong: fast statuses/latencies.
        traffic = StageTraffic(
            statuses=np.full(n, 200), latencies_ms=np.full(n, 2.0),
            config_hashes=["cand"] * n, actions=[[0.0] * A] * n,
            households=[f"h{i}" for i in range(n)],
        )
        plan = StagePlan(index=0, percent=100.0, is_promote=True)
        report = controller._evaluate_stage(plan, traffic, since_ts=100.0)
        assert not report.ok
        assert any("p95" in r for r in report.reasons)
        cand_arm = report.arms["cand"]
        # Server-side number judged; the wire number demoted to detail.
        assert cand_arm["p95_ms"] > 500.0
        assert cand_arm["client_p95_ms"] <= 2.0
        assert cand_arm["server_requests"] == 16

    def test_no_server_rows_keeps_client_latency(self, tmp_path):
        from p2pmicrogrid_tpu.serve.promotion import (
            CanaryBudgets,
            CanaryController,
            StagePlan,
            StageTraffic,
        )

        db = str(tmp_path / "wh.db")
        ResultsStore(db).close()
        _Reg = _RegistryStub

        controller = CanaryController(
            _Reg(), candidate_hash="cand", incumbent_hash="inc",
            stages=(100.0,), budgets=CanaryBudgets(min_requests=4),
            results_db=db,
        )
        n = 4
        traffic = StageTraffic(
            statuses=np.full(n, 200), latencies_ms=np.full(n, 3.0),
            config_hashes=["cand"] * n, actions=[[0.0] * A] * n,
            households=[f"h{i}" for i in range(n)],
        )
        plan = StagePlan(index=0, percent=100.0, is_promote=True)
        report = controller._evaluate_stage(plan, traffic, since_ts=0.0)
        assert report.ok
        assert "client_p95_ms" not in report.arms["cand"]


# -- live fleet fixtures -------------------------------------------------------


@pytest.fixture(scope="module")
def crafted_incumbent(tmp_path_factory):
    cfg = _cfg()
    root = tmp_path_factory.mktemp("autopilot-bundles")
    return cfg, make_crafted_bundle(cfg, "incumbent", str(root / "incumbent"))


def _local_fleet(incumbent, db, n=2):
    from p2pmicrogrid_tpu.serve.router import LocalFleet

    return LocalFleet(
        [incumbent], n_replicas=n, max_batch=16, results_db=db,
        device="cpu", run_name="autopilot-test",
    )


# -- dynamic bundle registration ----------------------------------------------


def _admin_post(host, port, path, payload):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        conn.request(
            "POST", path, body=json.dumps(payload),
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


class TestAdminRegister:
    def test_register_route_unregister_flush(self, crafted_incumbent,
                                             tmp_path):
        import dataclasses as dc

        from p2pmicrogrid_tpu.serve.engine import PolicyEngine
        from p2pmicrogrid_tpu.serve.gateway import (
            GatewayServer,
            build_gateway,
        )

        cfg, incumbent = crafted_incumbent
        cand_cfg = cfg.replace(
            train=dc.replace(cfg.train, starting_episodes=777)
        )
        cand_dir = make_crafted_bundle(
            cand_cfg, "good", str(tmp_path / "cand")
        )
        db = str(tmp_path / "wh.db")
        gateway = build_gateway(
            [incumbent], max_batch=16, device="cpu", results_db=db
        )
        server = GatewayServer(gateway)
        host, port = server.start()
        try:
            status, doc = _admin_post(
                host, port, "/admin/register", {"bundle_dir": cand_dir}
            )
            assert status == 200 and doc["already_registered"] is False
            cand_hash = doc["config_hash"]
            assert cand_hash in doc["bundles"]
            # Idempotent: a fleet push retrying must converge, not 409.
            status, doc = _admin_post(
                host, port, "/admin/register", {"bundle_dir": cand_dir}
            )
            assert status == 200 and doc["already_registered"] is True
            # The runtime-registered bundle actually serves: swap to it
            # and check a real act answer bit-exact against its engine.
            status, _ = _admin_post(
                host, port, "/admin/swap", {"config_hash": cand_hash}
            )
            assert status == 200
            obs = synthetic_obs(2, A, seed=5)
            status, doc = _admin_post(
                host, port, "/v1/act",
                {"household": "h-reg", "obs": obs[0].tolist()},
            )
            assert status == 200 and doc["config_hash"] == cand_hash
            want = PolicyEngine(
                bundle_dir=cand_dir, max_batch=16, device="cpu"
            ).act(obs[:1])[0]
            # host-sync: wire JSON payloads, host data.
            np.testing.assert_array_equal(
                np.asarray(doc["actions"], np.float32), want
            )
            # The default cannot be unregistered (sequencing error)...
            status, doc = _admin_post(
                host, port, "/admin/unregister", {"config_hash": cand_hash}
            )
            assert status == 409
            # ...but after swapping back it can, and the registry shrinks.
            inc_hash = [
                h for h in gateway.registry.hashes if h != cand_hash
            ][0]
            _admin_post(host, port, "/admin/swap", {"config_hash": inc_hash})
            status, doc = _admin_post(
                host, port, "/admin/unregister", {"config_hash": cand_hash}
            )
            assert status == 200 and doc["was_registered"] is True
            assert cand_hash not in gateway.registry.hashes
            # Unknown hash: idempotent cleanup, not an error.
            status, doc = _admin_post(
                host, port, "/admin/unregister", {"config_hash": "nope"}
            )
            assert status == 200 and doc["was_registered"] is False
            status, doc = _admin_post(host, port, "/admin/flush", {})
            assert status == 200 and doc["flushed"] >= 1
        finally:
            server.stop()

    def test_clear_pins_via_swap(self, crafted_incumbent):
        from p2pmicrogrid_tpu.serve.gateway import (
            GatewayServer,
            build_gateway,
        )

        cfg, incumbent = crafted_incumbent
        gateway = build_gateway([incumbent], max_batch=16, device="cpu")
        server = GatewayServer(gateway)
        host, port = server.start()
        try:
            gateway.registry._pins["h1"] = gateway.registry.default_hash
            status, _ = _admin_post(
                host, port, "/admin/swap", {"clear_pins": True}
            )
            assert status == 200
            assert gateway.registry.pinned_count == 0
        finally:
            server.stop()


# -- unattended cycles over a live fleet ---------------------------------------


class TestAutopilotCycles:
    def test_honest_promotes_regressions_blocked(self, crafted_incumbent,
                                                 tmp_path):
        from p2pmicrogrid_tpu.serve.router import FleetRouter

        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        cfg, incumbent = crafted_incumbent
        db = str(tmp_path / "wh.db")
        fleet = _local_fleet(incumbent, db)
        reps = fleet.start()
        rows = []
        tel = Telemetry(
            run_id="autopilot-test", sinks=[SqliteSink(db)],
            manifest={"autopilot_role": "supervisor"},
        )
        try:
            router = FleetRouter(reps)
            pilot = Autopilot(
                cfg, router, incumbent_dir=incumbent,
                state_dir=str(tmp_path / "state"), results_db=db,
                telemetry=tel,
                stages=(25.0, 100.0), requests_per_cycle=64,
                canary_requests=48, n_households=12, rate_hz=256.0,
                seed=0, trace_steps=10, emit=rows.append,
            )
            state = pilot.run(
                2, inject_plan=parse_inject_plan(
                    "0:good,1:cost_regressed"
                ),
            )
        finally:
            tel.close()
            fleet.stop_all()
        assert state.promotions == 1 and state.blocked == 1
        assert state.bad_promotions == 0
        assert state.availability == 1.0
        assert [link["cycle"] for link in state.lineage] == [0]
        good, bad = rows[0], rows[1]
        assert good["promoted"] and good["serving_verified"]
        assert bad["blocked_at_gate"] and bad["serving_verified"]
        assert good["outcome_ok"] and bad["outcome_ok"]
        # The promotion advanced the incumbent: cycle 1 gated against
        # cycle 0's candidate, and the journal's lineage says so.
        assert bad["incumbent"] == good["candidate"]
        assert state.incumbent_hash == good["candidate"]
        # Cycle 1's export window started where cycle 0's new incumbent
        # began serving (watermark 0 for a fresh config is cycle 1's
        # first export; the second cycle of the SAME incumbent advances).
        with ResultsStore(db) as store:
            lineage = store.query_promotion_lineage()
        assert lineage["chain"][-1] == good["candidate"]
        # The journal is at rest and verifies.
        final = read_journal(str(tmp_path / "state"))
        assert final.phase == "idle" and final.cycle == 2


# -- SIGKILL crash recovery ----------------------------------------------------


def _autopilot_argv(incumbent, state_dir, db, out, replicas, cycles,
                    inject):
    argv = [
        sys.executable, "-m", "p2pmicrogrid_tpu.cli", "autopilot",
        "--incumbent", incumbent, "--state-dir", state_dir,
        "--results-db", db, "--cycles", str(cycles), "--inject", inject,
        "--out", out, "--requests-per-cycle", "48",
        "--canary-requests", "48", "--households", "12",
        "--stages", "25,100", "--agents", str(A),
        "--implementation", "tabular", "--seed", "0",
        "--trace-steps", "10", "--min-transitions", "4",
    ]
    for r in replicas:
        argv += ["--replica", f"{r.host}:{r.port}"]
    return argv


def _spawn_autopilot(argv, env):
    proc = subprocess.Popen(
        argv, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    lines: list = []
    threading.Thread(
        target=lambda: [lines.append(ln.rstrip()) for ln in proc.stdout],
        daemon=True,
    ).start()
    return proc, lines


def _kill_at_phase(proc, state_dir, cycle, phase, timeout_s=420.0):
    end = time.monotonic() + timeout_s
    while time.monotonic() < end and proc.poll() is None:
        try:
            st = read_journal(state_dir)
        except JournalCorrupt:
            st = None
        if st is not None and st.cycle == cycle and st.phase == phase:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
            return True
        time.sleep(0.1)
    return False


def _run_sigkill_case(cfg, incumbent, tmp_path, phase, inject,
                      expect):
    """SIGKILL the autopilot in ``phase`` of cycle 0, relaunch the SAME
    command line, assert the journal's recovery outcome and that the
    incumbent serves bit-exact afterwards."""
    from p2pmicrogrid_tpu.serve.engine import PolicyEngine

    db = str(tmp_path / "wh.db")
    state_dir = str(tmp_path / "state")
    out = str(tmp_path / "cycles.jsonl")
    fleet = _local_fleet(incumbent, db)
    reps = fleet.start()
    try:
        argv = _autopilot_argv(
            incumbent, state_dir, db, out, reps, cycles=1, inject=inject
        )
        env = dict(os.environ)
        env["P2P_AUTOPILOT_HOLD"] = json.dumps({phase: 8.0})
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["P2P_TELEMETRY"] = "0"
        proc, lines = _spawn_autopilot(argv, env)
        killed = _kill_at_phase(proc, state_dir, cycle=0, phase=phase)
        assert killed, f"kill window ({phase}) never opened:\n" + "\n".join(
            lines[-20:]
        )
        # Mid-flight state on disk, journal mid-phase: relaunch with the
        # SAME command line — the journal drives recovery.
        proc, lines = _spawn_autopilot(argv, env)
        rc = proc.wait(timeout=600)
        assert rc == 0, "\n".join(lines[-30:])
        final = read_journal(state_dir)
        assert final.cycle == 1 and final.phase == "idle"
        expect(final)
        # The fleet serves the journal's incumbent, bit-exact, with no
        # split and no pins left behind.
        inc_hash = final.incumbent_hash
        obs = synthetic_obs(2, A, seed=9)
        want = PolicyEngine(
            bundle_dir=final.incumbent_dir, max_batch=16, device="cpu"
        ).act(obs[:1])[0]
        for rep in reps:
            status, doc = _admin_post(
                rep.host, rep.port, "/v1/act",
                {"household": "post-crash", "obs": obs[0].tolist()},
            )
            assert status == 200 and doc["config_hash"] == inc_hash
            # host-sync: wire JSON payloads, host data.
            np.testing.assert_array_equal(
                np.asarray(doc["actions"], np.float32), want
            )
            entry = fleet.entry(rep.replica_id)
            assert entry["registry"].split is None
            assert entry["registry"].pinned_count == 0
    finally:
        fleet.stop_all()


class TestSigkillRecovery:
    def test_mid_retrain_rerun_completes_cycle(self, crafted_incumbent,
                                               tmp_path):
        cfg, incumbent = crafted_incumbent

        def expect(final):
            # Re-runnable phase: the cycle re-ran and finished normally —
            # the crafted regression still blocked, no crash abort.
            assert final.blocked == 1
            assert final.crash_aborts == 0
            assert final.promotions == 0

        _run_sigkill_case(
            cfg, incumbent, tmp_path, phase="retraining",
            inject="0:cost_regressed", expect=expect,
        )

    def test_mid_canary_aborts_to_incumbent(self, crafted_incumbent,
                                            tmp_path):
        cfg, incumbent = crafted_incumbent

        def expect(final):
            # Canary crash: abort back to the incumbent — the good
            # candidate is NOT promoted (safety beats progress), the
            # split is gone and the cycle is accounted as a crash abort.
            assert final.crash_aborts == 1
            assert final.promotions == 0
            assert final.candidate_hash is None

        _run_sigkill_case(
            cfg, incumbent, tmp_path, phase="canarying",
            inject="0:good", expect=expect,
        )
