"""Parallel-layer tests on the virtual 8-device CPU mesh (conftest.py).

Covers SURVEY.md section 4's TPU-specific oracles: single-device-vs-sharded
equivalence and scenario-batch mechanics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import DQNConfig, SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.parallel import (
    init_shared_state,
    make_mesh,
    make_scenario_traces,
    stack_scenario_arrays,
    train_scenarios_independent,
    train_scenarios_shared,
)
from p2pmicrogrid_tpu.parallel.mesh import replicate, shard_leading_axis, shard_scen_state
from p2pmicrogrid_tpu.train import init_policy_state, make_policy

# Whole module is compile-heavy (sharded-vs-single episode equivalence compiles).
pytestmark = pytest.mark.slow

S = 8


@pytest.fixture(scope="module")
def setup():
    cfg = default_config(
        sim=SimConfig(n_agents=2, n_scenarios=S),
        train=TrainConfig(implementation="tabular"),
        dqn=DQNConfig(buffer_size=128, batch_size=8),
    )
    ratings = make_ratings(cfg, np.random.default_rng(42))
    traces = make_scenario_traces(cfg)  # S from cfg.sim.n_scenarios
    arrays = stack_scenario_arrays(cfg, traces, ratings)
    return cfg, ratings, arrays


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert mesh.devices.size == 8


def test_scenario_traces_differ(setup):
    _, _, arrays = setup
    # Each scenario is an independent draw.
    assert not np.allclose(np.asarray(arrays.load_w[0]), np.asarray(arrays.load_w[1]))


def test_independent_training_runs_sharded(setup):
    cfg, ratings, arrays = setup
    mesh = make_mesh()
    key = jax.random.PRNGKey(0)
    policy = make_policy(cfg)
    ps_s = jax.vmap(lambda k: init_policy_state(cfg, k))(jax.random.split(key, S))
    ps_s = shard_leading_axis(ps_s, mesh)
    arrays_sh = shard_leading_axis(arrays, mesh)

    ps2, rewards, _, _ = train_scenarios_independent(
        cfg, policy, ps_s, arrays_sh, ratings, key, n_episodes=2
    )
    assert rewards.shape == (2, S)
    assert np.isfinite(rewards).all()
    # Result keeps the scenario sharding (each device trained its scenario).
    assert "data" in str(ps2.q_table.sharding)


def test_sharded_matches_single_device(setup):
    """The same computation, scenario-sharded vs fully replicated on one
    device, must agree bit-for-bit modulo float reassociation."""
    cfg, ratings, arrays = setup
    key = jax.random.PRNGKey(0)
    policy = make_policy(cfg)
    ps_s = jax.vmap(lambda k: init_policy_state(cfg, k))(jax.random.split(key, S))

    mesh = make_mesh()
    ps_sh = shard_leading_axis(ps_s, mesh)
    arrays_sh = shard_leading_axis(arrays, mesh)

    out_sharded, r_sharded, _, _ = train_scenarios_independent(
        cfg, policy, ps_sh, arrays_sh, ratings, key, n_episodes=1
    )
    out_single, r_single, _, _ = train_scenarios_independent(
        cfg, policy, ps_s, arrays, ratings, key, n_episodes=1
    )
    np.testing.assert_allclose(r_sharded, r_single, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out_sharded.q_table), np.asarray(out_single.q_table), rtol=1e-5
    )


def test_shared_tabular_single_table(setup):
    cfg, ratings, arrays = setup
    key = jax.random.PRNGKey(0)
    policy = make_policy(cfg)
    ps = init_policy_state(cfg, key)
    ps2, _, rewards, _, _ = train_scenarios_shared(
        cfg, policy, ps, arrays, ratings, key, n_episodes=1
    )
    assert rewards.shape == (1, S)
    # One shared table (no scenario axis) actually learned.
    assert ps2.q_table.shape == ps.q_table.shape
    assert float(jnp.abs(ps2.q_table - ps.q_table).max()) > 0.0
    # Episode 0 decays exploration on the reference cadence.
    assert float(ps2.epsilon) < float(ps.epsilon)


def test_shared_dqn_runs(setup):
    cfg, ratings, arrays = setup
    cfg = cfg.replace(train=TrainConfig(implementation="dqn"))
    from p2pmicrogrid_tpu.parallel import init_shared_state

    key = jax.random.PRNGKey(0)
    policy = make_policy(cfg)
    ps, repl = init_shared_state(cfg, key)
    ps2, repl2, rewards, _, _ = train_scenarios_shared(
        cfg, policy, ps, arrays, ratings, key, n_episodes=1, replay_s=repl
    )
    assert rewards.shape == (1, S)
    # Time-major lockstep replay: [cap, S, A, ...], separate from pol_state.
    assert repl2.obs.shape[1] == S
    assert int(np.asarray(repl2.count)) == 96
    d = np.abs(
        np.asarray(ps2.online["Dense_0"]["kernel"])
        - np.asarray(ps.online["Dense_0"]["kernel"])
    ).max()
    assert d > 0

class TestSharedDDPG:
    """Scenario-averaged shared actor-critic (BASELINE config 4's
    "shared-critic MARL"; the reference's actor-critic capability is the stale
    rl_backup.py:14-62)."""

    def _cfg(self, setup, share_across_agents):
        from p2pmicrogrid_tpu.config import DDPGConfig

        cfg, ratings, arrays = setup
        cfg = cfg.replace(
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(
                buffer_size=128, batch_size=8,
                share_across_agents=share_across_agents,
            ),
        )
        return cfg, ratings, arrays

    @pytest.mark.parametrize("share", [False, True])
    def test_runs_and_learns(self, setup, share):
        from p2pmicrogrid_tpu.parallel import init_shared_state

        cfg, ratings, arrays = self._cfg(setup, share)
        policy = make_policy(cfg)
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(1))
        ps2, scen2, rewards, losses, _ = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(0),
            n_episodes=1, replay_s=scen,
        )
        assert rewards.shape == (1, S)
        assert np.isfinite(rewards).all()
        # Real (non-zero) critic loss is reported (round-1 VERDICT weak #5).
        assert losses.shape == (1, S)
        assert float(np.abs(losses).max()) > 0.0
        # Shared params actually moved; per-agent mode keeps the agent axis,
        # agent-shared mode has none.
        kernel = ps2.actor["Dense_0"]["kernel"]
        assert kernel.ndim == (3 if not share else 2)
        moved = jax.tree_util.tree_map(
            lambda a, b: float(jnp.abs(a - b).max()), ps.actor, ps2.actor
        )
        assert max(jax.tree_util.tree_leaves(moved)) > 0.0
        # OU noise state evolved per scenario, replay filled.
        assert not np.allclose(np.asarray(scen.ou), np.asarray(scen2.ou))
        assert int(np.asarray(scen2.replay.count).reshape(-1)[0]) == 96

    def test_sharded_matches_single_device(self, setup):
        from p2pmicrogrid_tpu.parallel import init_shared_state

        cfg, ratings, arrays = self._cfg(setup, False)
        policy = make_policy(cfg)
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(1))

        mesh = make_mesh()
        scen_sh = shard_scen_state(scen, mesh)
        arrays_sh = shard_leading_axis(arrays, mesh)

        ps_sh, _, r_sh, l_sh, _ = train_scenarios_shared(
            cfg, policy, ps, arrays_sh, ratings, jax.random.PRNGKey(0),
            n_episodes=1, replay_s=scen_sh,
        )
        ps_1d, _, r_1d, l_1d, _ = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(0),
            n_episodes=1, replay_s=scen,
        )
        np.testing.assert_allclose(r_sh, r_1d, rtol=1e-4)
        np.testing.assert_allclose(l_sh, l_1d, rtol=1e-3)
        np.testing.assert_allclose(
            np.asarray(ps_sh.actor["Dense_0"]["kernel"]),
            np.asarray(ps_1d.actor["Dense_0"]["kernel"]),
            rtol=1e-4, atol=1e-6,
        )


class TestHybridMesh:
    """Multi-host (DCN x ICI) mesh shape, exercised as 2 virtual hosts x 4
    chips on the CPU mesh — the same sharded program a pod would run."""

    def test_hybrid_mesh_shape_and_sharding(self, setup):
        from p2pmicrogrid_tpu.parallel.mesh import (
            hybrid_scenario_sharding,
            make_hybrid_mesh,
        )

        mesh = make_hybrid_mesh(dcn_size=2)
        assert mesh.devices.shape == (2, 4)
        sh = hybrid_scenario_sharding(mesh)
        x = jax.device_put(jnp.arange(16.0).reshape(8, 2), sh)
        # The leading axis splits over all 8 devices (hosts x chips).
        assert len(x.sharding.device_set) == 8

    def test_hybrid_grid_2d_on_sliced_topology(self):
        """Regression (round-2 ADVICE): on real sliced TPU topologies the
        topology-aware branch returned a 1-D grid (elementwise product of the
        1-D shape tuples), so Mesh() raised on exactly the pod path this mesh
        exists for. The grid request must be 2-D on both axes."""
        from p2pmicrogrid_tpu.parallel.mesh import _hybrid_grid

        class FakeDev:
            # The attribute set mesh_utils consults for sliced TPU topologies.
            platform = "tpu"
            device_kind = "fake"
            core_on_chip = 0

            def __init__(self, i, slice_i):
                self.id = i
                self.process_index = slice_i
                self.slice_index = slice_i
                self.coords = (i % 4, 0, 0)

        devs = [FakeDev(i, i // 4) for i in range(8)]
        grid = _hybrid_grid(devs, n_hosts=2)
        assert grid.shape == (2, 4)
        # Each row = one slice/host: collectives inside a row ride ICI.
        for row in range(2):
            assert {d.slice_index for d in grid[row]} == {row}

    def test_shared_training_on_hybrid_mesh_matches_1d(self, setup):
        from p2pmicrogrid_tpu.parallel.mesh import (
            hybrid_scenario_sharding,
            make_hybrid_mesh,
        )

        cfg, ratings, arrays = setup
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))

        mesh = make_hybrid_mesh(dcn_size=2)
        sh = hybrid_scenario_sharding(mesh)
        arrays_h = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), arrays
        )
        ps_h, _, r_h, _, _ = train_scenarios_shared(
            cfg, policy, ps, arrays_h, ratings, jax.random.PRNGKey(0), n_episodes=1
        )
        ps_1, _, r_1, _, _ = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(0), n_episodes=1
        )
        np.testing.assert_allclose(r_h, r_1, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(ps_h.q_table), np.asarray(ps_1.q_table), rtol=1e-5
        )


def test_shared_dqn_warmup_records_without_learning(setup):
    """warmup_shared_dqn (the reference's init_buffers, community.py:125-147):
    fills the lockstep replay, leaves online params untouched, hard-copies
    online -> target."""
    from p2pmicrogrid_tpu.parallel import init_shared_state, warmup_shared_dqn

    cfg, ratings, arrays = setup
    cfg = cfg.replace(
        train=TrainConfig(implementation="dqn"),
        dqn=DQNConfig(buffer_size=128, batch_size=8, warmup_passes=2),
    )
    policy = make_policy(cfg)
    ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
    ps2, scen2 = warmup_shared_dqn(
        cfg, policy, ps, scen, arrays, ratings, jax.random.PRNGKey(1)
    )
    # Two record-only passes over the 96-slot day.
    assert int(np.asarray(scen2.count)) == 128  # capped at buffer size
    np.testing.assert_array_equal(
        np.asarray(ps2.online["Dense_0"]["kernel"]),
        np.asarray(ps.online["Dense_0"]["kernel"]),
    )
    # Hard target copy.
    np.testing.assert_array_equal(
        np.asarray(ps2.target["Dense_0"]["kernel"]),
        np.asarray(ps2.online["Dense_0"]["kernel"]),
    )


def test_shared_dqn_and_ddpg_report_per_scenario_loss(setup):
    """Round-2 VERDICT weak #7: shared DQN/DDPG reported one broadcast mean
    for every scenario; the per-sample residuals must unflatten back to a
    real per-scenario loss with nonzero cross-scenario variance."""
    import dataclasses

    from p2pmicrogrid_tpu.config import DDPGConfig
    from p2pmicrogrid_tpu.parallel import init_shared_state

    cfg, ratings, arrays = setup
    for impl in ("dqn", "ddpg"):
        cfg_i = cfg.replace(
            train=dataclasses.replace(cfg.train, implementation=impl),
            dqn=DQNConfig(buffer_size=16, batch_size=4),
            ddpg=DDPGConfig(buffer_size=16, batch_size=4),
        )
        policy = make_policy(cfg_i)
        ps, scen = init_shared_state(cfg_i, jax.random.PRNGKey(0))
        if impl == "dqn":
            from p2pmicrogrid_tpu.parallel import warmup_shared_dqn

            ps, scen = warmup_shared_dqn(
                cfg_i, policy, ps, scen, arrays, ratings, jax.random.PRNGKey(3)
            )
        _, _, _, losses, _ = train_scenarios_shared(
            cfg_i, policy, ps, arrays, ratings, jax.random.PRNGKey(1),
            n_episodes=1, replay_s=scen,
        )
        assert np.isfinite(losses).all()
        assert np.asarray(losses)[0].std() > 0.0, (
            f"{impl}: per-scenario losses are identical — broadcast mean?"
        )


def test_shared_tabular_reports_real_td_error(setup):
    # The shared-tabular update must report the agent-mean squared TD error
    # per scenario, not zeros (round-1 VERDICT weak #5).
    cfg, ratings, arrays = setup
    policy = make_policy(cfg)
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    _, _, _, losses, _ = train_scenarios_shared(
        cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(0), n_episodes=1
    )
    assert losses.shape == (1, S)
    assert float(np.abs(losses).max()) > 0.0


def test_shared_params_stay_replicated_on_mesh():
    """Intended placement for shared policy state on a mesh: REPLICATED —
    every device applies the identical all-reduced update to its local copy
    so no slot moves the shared table/nets over ICI. Left unplaced, XLA
    parks the updated tabular state on ONE device (round-4 dryruns showed
    'params over 1 devices'), which on a real pod becomes a per-slot
    broadcast of the whole Q-table (__graft_entry__.dryrun_multichip
    asserts the same invariant across all shared modes)."""
    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest forces the 8-device virtual CPU mesh"
    mesh = make_mesh(n_dev)
    cfg = default_config(
        sim=SimConfig(n_agents=2, n_scenarios=n_dev),
        train=TrainConfig(implementation="tabular"),
    )
    ratings = make_ratings(cfg, np.random.default_rng(0))
    arrays = stack_scenario_arrays(
        cfg, make_scenario_traces(cfg, n_dev), ratings
    )
    arrays = jax.tree_util.tree_map(lambda x: x[:, :4], arrays)
    policy = make_policy(cfg)
    ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
    ps_out, _, _, _, _ = train_scenarios_shared(
        cfg, policy, replicate(ps, mesh), shard_leading_axis(arrays, mesh),
        ratings, jax.random.PRNGKey(1), n_episodes=1,
        replay_s=shard_scen_state(scen, mesh),
    )
    for leaf in jax.tree_util.tree_leaves(ps_out):
        assert len(leaf.sharding.device_set) == n_dev, leaf.sharding
