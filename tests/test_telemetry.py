"""Telemetry subsystem: registry aggregates/sinks, span nesting + Chrome
trace export, JSONL round-trip, stdout hygiene, and device-counter
correctness under jit (NaN injection; comfort-violation count vs a numpy
recomputation of the same episode)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.telemetry import (
    JsonlSink,
    MemorySink,
    Telemetry,
    dc_add,
    dc_from_slot,
    dc_to_dict,
    dc_zero,
    guarded_stdout_sink,
)


class TestRegistry:
    def test_counters_gauges_histograms_aggregate(self):
        tel = Telemetry(run_id="t")
        tel.counter("a")
        tel.counter("a", 4)
        tel.gauge("g", 1.0)
        tel.gauge("g", 2.5)
        for v in (1.0, 2.0, 3.0, 4.0):
            tel.histogram("h", v)
        s = tel.summary()
        assert s["counters"]["a"] == 5.0
        assert s["gauges"]["g"] == 2.5
        h = s["histograms"]["h"]
        assert h["count"] == 4 and h["min"] == 1.0 and h["max"] == 4.0
        assert h["mean"] == pytest.approx(2.5)

    def test_events_reach_all_sinks_with_ts_and_kind(self):
        m1, m2 = MemorySink(), MemorySink()
        tel = Telemetry(run_id="t", sinks=[m1, m2])
        tel.event("health", episode=3, status="healthy")
        assert len(m1.records) == len(m2.records) == 1
        rec = m1.records[0]
        assert rec["kind"] == "health" and rec["episode"] == 3
        assert isinstance(rec["ts"], float)

    def test_emit_is_verbatim(self):
        # Bench metric rows must keep their exact schema — no decoration.
        m = MemorySink()
        tel = Telemetry(run_id="t", sinks=[m])
        row = {"metric": "x", "value": 1.0, "unit": "u", "vs_baseline": 2.0}
        tel.emit(row)
        assert m.records[0] == row

    def test_jsonl_round_trip(self, tmp_path):
        path = str(tmp_path / "metrics.jsonl")
        tel = Telemetry(run_id="t", sinks=[JsonlSink(path)])
        tel.event("a", x=1)
        tel.event("b", y=[1, 2], z="s")
        tel.event("c", w=np.float32(1.5))  # numpy scalars must serialize
        tel.close()
        recs = [json.loads(l) for l in open(path) if l.strip()]
        # close() appends a summary event after the three emitted ones.
        assert [r["kind"] for r in recs] == ["a", "b", "c", "summary"]
        assert recs[1]["y"] == [1, 2]
        assert recs[2]["w"] == 1.5

    def test_create_writes_manifest_and_close_writes_summary(self, tmp_path):
        cfg = default_config()
        tel = Telemetry.create("unit", cfg=cfg, root=str(tmp_path))
        tel.counter("c", 2)
        with tel.span("s"):
            pass
        tel.close()
        assert tel.run_dir is not None
        manifest = json.load(open(os.path.join(tel.run_dir, "manifest.json")))
        assert manifest["run_id"] == tel.run_id
        assert manifest["config_hash"]
        summary = json.load(open(os.path.join(tel.run_dir, "summary.json")))
        assert summary["counters"]["c"] == 2.0
        assert summary["spans"]["s"]["count"] == 1
        trace = json.load(open(os.path.join(tel.run_dir, "trace.json")))
        assert [e["name"] for e in trace["traceEvents"]] == ["s"]

    def test_maybe_create_honors_kill_switch(self, tmp_path, monkeypatch):
        monkeypatch.setenv("P2P_TELEMETRY", "0")
        assert Telemetry.maybe_create("x", root=str(tmp_path)) is None
        monkeypatch.setenv("P2P_TELEMETRY", "1")
        tel = Telemetry.maybe_create("x", root=str(tmp_path))
        assert tel is not None


class TestSpans:
    def test_nesting_and_durations(self):
        tel = Telemetry(run_id="t")
        with tel.span("outer"):
            with tel.span("inner", tag="x"):
                pass
        # Completion order: inner closes first.
        names = [s.name for s in tel.spans.completed]
        assert names == ["inner", "outer"]
        inner, outer = tel.spans.completed
        assert inner.depth == 1 and outer.depth == 0
        assert outer.duration >= inner.duration >= 0

    def test_chrome_trace_export(self):
        tel = Telemetry(run_id="t")
        with tel.span("a"):
            with tel.span("b"):
                pass
        trace = tel.spans.chrome_trace()
        events = {e["name"]: e for e in trace["traceEvents"]}
        assert set(events) == {"a", "b"}
        for e in events.values():
            assert e["ph"] == "X" and e["dur"] >= 0
        # Child interval is contained in the parent's.
        a, b = events["a"], events["b"]
        assert a["ts"] <= b["ts"]
        assert b["ts"] + b["dur"] <= a["ts"] + a["dur"] + 1e-3

    def test_duration_lookup_returns_most_recent(self):
        tel = Telemetry(run_id="t")
        with tel.span("x"):
            pass
        with tel.span("x"):
            pass
        assert tel.spans.duration("x") == tel.spans.completed[-1].duration
        assert tel.spans.duration("missing") is None

    def test_timed_runs_fn_under_span(self):
        tel = Telemetry(run_id="t")
        out = tel.timed("compute", lambda: jnp.arange(4).sum())
        assert int(out) == 6
        assert tel.spans.duration("compute") is not None


class TestStdoutHygiene:
    def test_guarded_sink_keeps_stdout_strictly_json(self, capfd):
        with guarded_stdout_sink() as sink:
            print("stray python noise")          # fd 1 -> stderr now
            os.write(1, b"stray fd noise\n")      # raw writes too
            sink.emit({"metric": "m", "value": 1.0, "unit": "u",
                       "vs_baseline": 2.0})
        out, err = capfd.readouterr()
        lines = [l for l in out.splitlines() if l.strip()]
        assert len(lines) == 1
        assert json.loads(lines[0])["metric"] == "m"
        assert "stray python noise" in err and "stray fd noise" in err


def _slot_outputs(q, loss, t_in, p_grid, p_p2p):
    """Minimal SlotOutputs for counter tests (unused fields zeroed)."""
    from p2pmicrogrid_tpu.envs.community import SlotOutputs

    z = jnp.zeros_like(jnp.asarray(t_in))
    return SlotOutputs(
        cost=z, reward=z, loss=jnp.asarray(loss), p_grid=jnp.asarray(p_grid),
        p_p2p=jnp.asarray(p_p2p), buy_price=jnp.zeros(()),
        injection_price=jnp.zeros(()), trade_price=jnp.zeros(()),
        t_in=jnp.asarray(t_in), hp_power_w=z, decisions=z[None],
        q=jnp.asarray(q),
    )


class TestDeviceCounters:
    def test_nan_and_inf_counted_under_jit(self):
        cfg = default_config(sim=SimConfig(n_agents=4))

        @jax.jit
        def count(q, loss):
            out = _slot_outputs(
                q, loss,
                t_in=jnp.full(4, 21.0),
                p_grid=jnp.zeros(4), p_p2p=jnp.zeros(4),
            )
            return dc_from_slot(cfg, out)

        q = jnp.array([1.0, jnp.nan, jnp.inf, 2.0])
        loss = jnp.array([0.0, 0.0, jnp.nan, 0.0])
        d = dc_to_dict(count(q, loss))
        assert d["nonfinite_q"] == 2
        assert d["nonfinite_loss"] == 1

    def test_comfort_and_market_counters(self):
        cfg = default_config(sim=SimConfig(n_agents=3))
        th = cfg.thermal
        out = _slot_outputs(
            q=jnp.zeros(3), loss=jnp.zeros(3),
            t_in=jnp.array([th.lower_bound - 0.5, th.setpoint,
                            th.upper_bound + 0.1]),
            p_grid=jnp.array([1000.0, -500.0, 0.0]),
            p_p2p=jnp.array([200.0, -200.0, 0.0]),
        )
        d = dc_to_dict(dc_from_slot(cfg, out))
        assert d["comfort_violations"] == 2
        assert d["market_residual_wh"] == pytest.approx(
            1500.0 * cfg.sim.slot_hours
        )
        assert d["trade_wh"] == pytest.approx(200.0 * cfg.sim.slot_hours)

    def test_accumulation_preserves_dtypes(self):
        a = dc_add(dc_zero(), dc_zero())
        assert a.nonfinite_q.dtype == jnp.int32
        assert a.market_residual_wh.dtype == jnp.float32

    def test_episode_counters_match_numpy_recomputation(self):
        """run_episode(collect_device_metrics=True): the in-scan comfort and
        market totals must equal a host recomputation from the recorded
        per-slot outputs."""
        from p2pmicrogrid_tpu.data import synthetic_traces
        from p2pmicrogrid_tpu.envs import (
            build_episode_arrays,
            init_physical,
            make_ratings,
            run_episode,
        )
        from p2pmicrogrid_tpu.train import init_policy_state, make_policy

        cfg = default_config(
            sim=SimConfig(n_agents=3),
            train=TrainConfig(implementation="tabular"),
        )
        traces = synthetic_traces(n_days=1, start_day=11).normalized()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, traces, ratings)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        phys = init_physical(cfg, jax.random.PRNGKey(1))

        fn = jax.jit(
            lambda ps, phys, k: run_episode(
                cfg, policy, ps, phys, arrays, ratings, k, training=True,
                collect_device_metrics=True,
            )
        )
        _, _, outputs, dc = fn(ps, phys, jax.random.PRNGKey(2))
        d = dc_to_dict(dc)

        t_in = np.asarray(outputs.t_in)          # [T, A] pre-step temps
        th = cfg.thermal
        want_viol = int(
            ((t_in < th.lower_bound) | (t_in > th.upper_bound)).sum()
        )
        assert d["comfort_violations"] == want_viol
        want_resid = float(
            np.abs(np.asarray(outputs.p_grid)).sum() * cfg.sim.slot_hours
        )
        assert d["market_residual_wh"] == pytest.approx(want_resid, rel=1e-4)
        want_trade = float(
            np.clip(np.asarray(outputs.p_p2p), 0.0, None).sum()
            * cfg.sim.slot_hours
        )
        assert d["trade_wh"] == pytest.approx(want_trade, rel=1e-4)
        assert d["nonfinite_q"] == 0 and d["nonfinite_loss"] == 0


@pytest.mark.slow
class TestHealthIntegration:
    def test_chunked_health_run_produces_run_dir(self, tmp_path):
        """train_chunked_with_health with an explicit Telemetry emits health
        events, device counters, spans, and a parseable run directory."""
        from p2pmicrogrid_tpu.config import DDPGConfig
        from p2pmicrogrid_tpu.envs import make_ratings
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state
        from p2pmicrogrid_tpu.train import make_policy
        from p2pmicrogrid_tpu.train.health import (
            HealthMonitor,
            train_chunked_with_health,
        )

        cfg = default_config(
            sim=SimConfig(n_agents=3, n_scenarios=2),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(buffer_size=32, batch_size=2,
                            share_across_agents=True),
        )
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        tel = Telemetry.create("test-health", cfg=cfg, root=str(tmp_path))
        train_chunked_with_health(
            cfg, policy, ps, ratings, jax.random.PRNGKey(7),
            n_episodes=2, n_chunks=2, eval_every=1, s_eval=2,
            monitor=HealthMonitor(96, warn_stream=open(os.devnull, "w")),
            telemetry=tel,
        )
        tel.close()
        recs = [
            json.loads(l)
            for l in open(os.path.join(tel.run_dir, "metrics.jsonl"))
        ]
        kinds = {r["kind"] for r in recs}
        assert {"health", "device_counters", "train_block",
                "health_summary"} <= kinds
        # Device counters were accumulated from the jitted eval scan.
        summary = json.load(open(os.path.join(tel.run_dir, "summary.json")))
        assert "device.comfort_violations" in summary["counters"]
        assert summary["spans"]["greedy_eval"]["count"] == 3
        # The run dir validates against the documented schema.
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "check_artifacts_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        problems: list = []
        mod.check_run_dir(tel.run_dir, problems)
        assert problems == []

    def test_untrained_reference_cost_accepts_counter_eval(self):
        """The resume path calibrates against a counter-collecting greedy
        eval (3-tuple return) — it must unpack either arity."""
        from p2pmicrogrid_tpu.config import DDPGConfig
        from p2pmicrogrid_tpu.envs import make_ratings
        from p2pmicrogrid_tpu.train import make_policy
        from p2pmicrogrid_tpu.train.health import (
            make_greedy_eval,
            untrained_reference_cost,
        )

        cfg = default_config(
            sim=SimConfig(n_agents=3, n_scenarios=2),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(buffer_size=32, batch_size=2,
                            share_across_agents=True),
        )
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ev = make_greedy_eval(
            cfg, policy, ratings, s_eval=2, collect_device_metrics=True
        )
        c = untrained_reference_cost(cfg, policy, ev, seed=0)
        assert np.isfinite(c)

    def test_train_community_telemetry(self, tmp_path):
        """train_community emits progress events and device.* counters."""
        from p2pmicrogrid_tpu.data import synthetic_traces
        from p2pmicrogrid_tpu.envs import make_ratings
        from p2pmicrogrid_tpu.train import (
            init_policy_state,
            make_policy,
            train_community,
        )

        cfg = default_config(
            sim=SimConfig(n_agents=2),
            train=TrainConfig(implementation="tabular", max_episodes=2),
        )
        traces = synthetic_traces(n_days=1, start_day=11).normalized()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        tel = Telemetry.create("test-train", cfg=cfg, root=str(tmp_path))
        train_community(
            cfg, policy, ps, traces, ratings, jax.random.PRNGKey(0),
            telemetry=tel,
        )
        tel.close()
        summary = json.load(open(os.path.join(tel.run_dir, "summary.json")))
        assert summary["counters"]["device.comfort_violations"] >= 0
        assert summary["spans"]["train_block"]["count"] >= 1
        recs = [
            json.loads(l)
            for l in open(os.path.join(tel.run_dir, "metrics.jsonl"))
        ]
        assert any(r["kind"] == "progress" for r in recs)


class TestReplayFillGauge:
    def test_fill_fraction_over_replay_carriers(self):
        from p2pmicrogrid_tpu.models.replay import (
            lockstep_replay_add,
            lockstep_replay_init,
        )
        from p2pmicrogrid_tpu.telemetry import replay_fill_fraction

        replay = lockstep_replay_init(2, 3, capacity=4)
        assert float(replay_fill_fraction(replay)) == 0.0
        for _ in range(2):
            replay = lockstep_replay_add(
                replay,
                jnp.zeros((2, 3, 4)), jnp.zeros((2, 3, 1)),
                jnp.zeros((2, 3)), jnp.zeros((2, 3, 4)),
            )
        assert float(replay_fill_fraction(replay)) == pytest.approx(0.5)
        # Wrapped carriers (DDPGScenState-style .replay field) resolve too.
        from p2pmicrogrid_tpu.parallel.scenarios import DDPGScenState

        scen = DDPGScenState(replay=replay, ou=jnp.zeros((2, 3)))
        assert float(replay_fill_fraction(scen)) == pytest.approx(0.5)
        # Stateless learners report None so callers skip the gauge.
        assert replay_fill_fraction(None) is None
        from p2pmicrogrid_tpu.models.tabular import tabular_init

        assert replay_fill_fraction(tabular_init(default_config().qlearning, 2)) is None


class TestSharedEpisodeCounters:
    def test_shared_training_scan_collects_counters(self):
        """make_shared_episode_fn(collect_device_metrics=True): the TRAINING
        slot scan accumulates the same in-program counters the greedy eval
        collects (ROADMAP open item)."""
        from p2pmicrogrid_tpu.envs import make_ratings
        from p2pmicrogrid_tpu.parallel import (
            init_shared_state,
            make_scenario_traces,
            stack_scenario_arrays,
        )
        from p2pmicrogrid_tpu.parallel.scenarios import make_shared_episode_fn
        from p2pmicrogrid_tpu.train import make_policy

        cfg = default_config(
            sim=SimConfig(n_agents=3, n_scenarios=2),
            train=TrainConfig(implementation="tabular"),
        )
        ratings = make_ratings(cfg, np.random.default_rng(0))
        traces = make_scenario_traces(cfg, seed=0)
        arrays = stack_scenario_arrays(cfg, traces, ratings)
        policy = make_policy(cfg)
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
        fn = make_shared_episode_fn(
            cfg, policy, arrays, ratings, collect_device_metrics=True
        )
        (ps, _), ys = fn((ps, scen), jax.random.PRNGKey(1))
        assert len(ys) == 3
        d = dc_to_dict(ys[2])
        assert d["nonfinite_q"] == 0 and d["nonfinite_loss"] == 0
        assert d["comfort_violations"] >= 0
        assert d["market_residual_wh"] > 0.0  # a day of grid settlement
        # The default (collect off) keeps the 2-tuple contract.
        fn2 = make_shared_episode_fn(cfg, policy, arrays, ratings)
        _, ys2 = fn2((ps, scen), jax.random.PRNGKey(1))
        assert len(ys2) == 2


class TestCompareRuns:
    def _make_run(self, root, name, counter, git_rev):
        tel = Telemetry.create(name, root=str(root))
        tel.manifest["config_hash"] = "abc123"
        tel.manifest["git_rev"] = git_rev
        import json as _json
        import os as _os

        with open(_os.path.join(tel.run_dir, "manifest.json"), "w") as f:
            _json.dump(tel.manifest, f)
        tel.counter("train.episodes", counter)
        tel.gauge("replay.fill_fraction", 0.25)
        with tel.span("train_block"):
            pass
        tel.close()
        return tel.run_dir

    def test_compare_runs_diffs_and_keys_identity(self, tmp_path):
        from p2pmicrogrid_tpu.telemetry.report import compare_runs

        a = self._make_run(tmp_path, "a", counter=10, git_rev="rev-a")
        b = self._make_run(tmp_path, "b", counter=25, git_rev="rev-b")
        text = compare_runs(a, b)
        assert "config_hash" in text and "match" in text
        assert "git_rev" in text and "DIFFERS" in text
        assert "train.episodes" in text
        assert "+15" in text  # counter delta
        assert "replay.fill_fraction" in text
        assert "train_block" in text

    def test_cli_compare(self, tmp_path, capsys):
        from p2pmicrogrid_tpu.cli import main

        a = self._make_run(tmp_path, "a", counter=1, git_rev="r")
        b = self._make_run(tmp_path, "b", counter=2, git_rev="r")
        assert main(["telemetry-report", "--compare", a, b]) == 0
        out = capsys.readouterr().out
        assert "identity" in out and "counters" in out
        assert main(["telemetry-report", "--compare", a, str(tmp_path / "x")]) == 1


class TestSqliteSink:
    """The telemetry warehouse: events/aggregates/spans stream into the
    results store's SQLite tables, keyed by the manifest's config_hash so
    one SQL join links a run's telemetry to its eval rows (ISSUE 3)."""

    def _run(self, db, config_hash="cfg-1", run_id="run-1"):
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        tel = Telemetry(
            run_id=run_id, sinks=[SqliteSink(db, batch=4)],
            manifest={
                "created": "2026-01-01T00:00:00", "config_hash": config_hash,
                "git_rev": "rev-1", "setting": "2-agent", "backend": "cpu",
                "device_count": 8,
            },
        )
        tel.counter("device.comfort_violations", 7)
        tel.gauge("profile.episode_scan.flops", 1234.0)
        tel.histogram("serve.batch_ms", 1.5)
        tel.histogram("serve.batch_ms", 2.5)
        tel.event("device_counters", episode=0, phase="train", trade_wh=3.0)
        tel.emit({"metric": "serve_bench", "value": 9.0, "unit": "ms",
                  "vs_baseline": 1.1})
        with tel.span("train_block", episodes=2):
            pass
        tel.close()
        return tel

    def test_round_trip_events_to_tables(self, tmp_path):
        from p2pmicrogrid_tpu.data.results import ResultsStore

        db = str(tmp_path / "r.db")
        self._run(db)
        with ResultsStore(db) as store:
            runs = store.con.execute(
                "SELECT run_id, config_hash, git_rev, setting "
                "FROM telemetry_runs"
            ).fetchall()
            assert runs == [("run-1", "cfg-1", "rev-1", "2-agent")]
            kinds = dict(
                store.con.execute(
                    "SELECT kind, COUNT(*) FROM telemetry_points "
                    "GROUP BY kind"
                ).fetchall()
            )
            # Streamed events + the close()-time aggregate explosion.
            assert kinds["device_counters"] == 1
            assert kinds["metric"] == 1
            assert kinds["counter"] == 1
            assert kinds["gauge"] == 1
            assert kinds["histogram"] == 1
            spans = store.con.execute(
                "SELECT name, depth FROM telemetry_spans"
            ).fetchall()
            assert spans == [("train_block", 0)]
            # The metric row kept its name/value as queryable columns.
            (val,) = store.con.execute(
                "SELECT value FROM telemetry_points "
                "WHERE kind='metric' AND name='serve_bench'"
            ).fetchone()
            assert val == 9.0
            assert store.get_run_gauges("run-1") == {
                "profile.episode_scan.flops": 1234.0
            }

    def test_schema_version_migration_from_fresh_and_legacy_db(self, tmp_path):
        import sqlite3

        from p2pmicrogrid_tpu.data.results import (
            TELEMETRY_SCHEMA_VERSION,
            ResultsStore,
            ensure_telemetry_schema,
        )

        # Fresh DB: open stamps the version and creates the tables.
        db = str(tmp_path / "fresh.db")
        with ResultsStore(db) as store:
            (v,) = store.con.execute("PRAGMA user_version").fetchone()
            assert v == TELEMETRY_SCHEMA_VERSION

        # Legacy pre-warehouse DB (classic tables, version 0): migrates in
        # place on open, keeping its rows.
        legacy = str(tmp_path / "legacy.db")
        con = sqlite3.connect(legacy)
        con.execute(
            "CREATE TABLE training_progress (setting text, "
            "implementation text, episode integer, reward real, error real)"
        )
        con.execute(
            "INSERT INTO training_progress VALUES ('s', 'tabular', 0, 1.0, 0.1)"
        )
        con.commit()
        assert con.execute("PRAGMA user_version").fetchone() == (0,)
        con.close()
        with ResultsStore(legacy) as store:
            assert store.con.execute(
                "PRAGMA user_version"
            ).fetchone() == (TELEMETRY_SCHEMA_VERSION,)
            assert store.con.execute(
                "SELECT COUNT(*) FROM telemetry_runs"
            ).fetchone() == (0,)
            assert len(store.get_training_progress()) == 1
            # Idempotent re-ensure.
            assert ensure_telemetry_schema(store.con) == TELEMETRY_SCHEMA_VERSION

    def test_join_telemetry_to_eval_on_config_hash(self, tmp_path):
        from p2pmicrogrid_tpu.data.results import ResultsStore

        db = str(tmp_path / "r.db")
        self._run(db, config_hash="cfg-A", run_id="run-A")
        self._run(db, config_hash="cfg-ORPHAN", run_id="run-orphan")
        with ResultsStore(db) as store:
            store.log_eval_run(
                "2-agent", "tabular", False, config_hash="cfg-A",
                git_rev="rev-1", n_days=2, total_cost_eur=-1.25,
            )
            rows = store.query_telemetry_joined()
        # Exactly ONE joined row: the matching config_hash pair; the orphan
        # run joins nothing.
        assert len(rows) == 1
        row = rows[0]
        assert row["run_id"] == "run-A"
        assert row["config_hash"] == "cfg-A"
        assert row["eval_setting"] == "2-agent"
        assert row["total_cost_eur"] == pytest.approx(-1.25)
        assert row["n_gauges"] == 1

    def test_cli_telemetry_query_returns_joined_row(self, tmp_path, capsys):
        """Acceptance: `telemetry-query` prints a single joined row linking
        a training run's telemetry gauges to its eval result by
        config_hash."""
        from p2pmicrogrid_tpu.cli import main
        from p2pmicrogrid_tpu.data.results import ResultsStore

        db = str(tmp_path / "r.db")
        self._run(db, config_hash="cfg-J", run_id="run-J")
        with ResultsStore(db) as store:
            store.log_eval_run(
                "2-agent", "tabular", False, config_hash="cfg-J",
                n_days=1, total_cost_eur=0.5,
            )
        assert main(["telemetry-query", "--results-db", db, "--gauges"]) == 0
        lines = [
            json.loads(l) for l in capsys.readouterr().out.splitlines() if l
        ]
        assert len(lines) == 1
        assert lines[0]["config_hash"] == "cfg-J"
        assert lines[0]["total_cost_eur"] == 0.5
        assert lines[0]["gauges"]["profile.episode_scan.flops"] == 1234.0
        # --sql escape hatch.
        assert main([
            "telemetry-query", "--results-db", db,
            "--sql", "SELECT COUNT(*) AS n FROM telemetry_spans",
        ]) == 0
        out = capsys.readouterr().out
        assert json.loads(out.splitlines()[-1])["n"] == 1

    def test_sink_threaded_emit(self, tmp_path):
        """The serve microbatch worker emits from its own thread; the sink
        must not corrupt or drop rows."""
        import threading

        from p2pmicrogrid_tpu.data.results import ResultsStore
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        db = str(tmp_path / "r.db")
        tel = Telemetry(run_id="t", sinks=[SqliteSink(db, batch=8)],
                        manifest={"config_hash": "x", "created": "t"})

        def emit_many(tag):
            for i in range(50):
                tel.event("serve_request", tag=tag, request=i)

        threads = [
            threading.Thread(target=emit_many, args=(t,)) for t in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tel.close()
        with ResultsStore(db) as store:
            (n,) = store.con.execute(
                "SELECT COUNT(*) FROM telemetry_points "
                "WHERE kind='serve_request'"
            ).fetchone()
        assert n == 200


class TestMeshCounters:
    """Multi-host metric aggregation (ROADMAP): per-device partial counters
    psum across the mesh INSIDE the jitted program — exercised on the
    virtual 8-device CPU mesh."""

    def _partials(self, n):
        from p2pmicrogrid_tpu.telemetry import DeviceCounters

        return DeviceCounters(
            nonfinite_q=jnp.arange(n, dtype=jnp.int32),
            nonfinite_loss=jnp.ones((n,), jnp.int32),
            comfort_violations=jnp.full((n,), 2, jnp.int32),
            market_residual_wh=jnp.arange(n, dtype=jnp.float32) * 1.5,
            trade_wh=jnp.ones((n,), jnp.float32),
        )

    def test_mesh_sum_matches_host_sum_1d(self):
        from p2pmicrogrid_tpu.parallel import make_mesh
        from p2pmicrogrid_tpu.telemetry import dc_mesh_sum

        mesh = make_mesh()
        n = mesh.devices.size
        tot = dc_mesh_sum(self._partials(n), mesh)
        d = dc_to_dict(tot)
        assert d["nonfinite_q"] == sum(range(n))
        assert d["comfort_violations"] == 2 * n
        assert d["market_residual_wh"] == pytest.approx(1.5 * sum(range(n)))
        # The reduction ran in-program: the result is a replicated device
        # array (every device holds the global total), not a host sum.
        assert tot.nonfinite_q.sharding.is_fully_replicated

    def test_mesh_sum_matches_host_sum_hybrid(self):
        """The 2-D (dcn x data) pod mesh: psum spans BOTH axes."""
        from p2pmicrogrid_tpu.parallel import make_hybrid_mesh
        from p2pmicrogrid_tpu.telemetry import dc_mesh_sum

        mesh = make_hybrid_mesh(dcn_size=2)
        n = mesh.devices.size
        d = dc_to_dict(dc_mesh_sum(self._partials(n), mesh))
        assert d["nonfinite_q"] == sum(range(n))
        assert d["trade_wh"] == pytest.approx(float(n))

    def test_dc_psum_inside_shard_map(self):
        """dc_psum is usable INSIDE a collective context: each shard
        contributes its local partial and every shard sees the global
        total."""
        from functools import partial

        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from p2pmicrogrid_tpu.parallel import make_mesh
        from p2pmicrogrid_tpu.telemetry import dc_psum

        mesh = make_mesh()
        n = mesh.devices.size
        dc = self._partials(n)

        @jax.jit
        @partial(shard_map, mesh=mesh, in_specs=P("data"), out_specs=P())
        def global_totals(dc):
            local = jax.tree_util.tree_map(lambda x: x.sum(axis=0), dc)
            return dc_psum(local, "data")

        d = dc_to_dict(global_totals(dc))
        assert d["nonfinite_q"] == sum(range(n))

    def test_mesh_manifest_records_shape_and_axes(self):
        from p2pmicrogrid_tpu.parallel import make_hybrid_mesh, mesh_manifest

        m = mesh_manifest(make_hybrid_mesh(dcn_size=2))
        assert m["mesh_shape"] == [2, 4]
        assert m["mesh_axis_names"] == ["dcn", "data"]
        assert m["mesh_device_count"] == 8

    def test_compare_identity_block_surfaces_mesh_shape(self, tmp_path):
        from p2pmicrogrid_tpu.telemetry.report import compare_runs

        dirs = []
        for name, shape in (("a", [8]), ("b", [2, 4])):
            tel = Telemetry.create(name, root=str(tmp_path))
            tel.annotate_manifest(
                mesh_shape=shape, mesh_axis_names=["data"], config_hash="h"
            )
            tel.close()
            dirs.append(tel.run_dir)
        text = compare_runs(*dirs)
        assert "mesh_shape" in text
        assert "[2, 4]" in text and "DIFFERS" in text


class TestProfiling:
    def test_profile_jitted_gauges_and_event(self):
        from p2pmicrogrid_tpu.telemetry import MemorySink, profile_jitted

        tel = Telemetry(run_id="t", sinks=[MemorySink()])
        f = jax.jit(lambda x: jnp.tanh(x @ x.T).sum())
        m = profile_jitted(
            f, jnp.ones((16, 16)), label="unit", telemetry=tel,
            extra={"note": "test"},
        )
        assert m["flops"] > 0
        assert m["peak_bytes"] > 0
        g = tel.summary()["gauges"]
        assert g["profile.unit.flops"] == m["flops"]
        assert g["profile.unit.peak_bytes"] == m["peak_bytes"]
        events = [
            r for r in tel.sinks[0].records if r["kind"] == "compile_profile"
        ]
        assert len(events) == 1 and events[0]["note"] == "test"

    def test_profile_and_compile_returns_runnable_executable(self):
        from p2pmicrogrid_tpu.telemetry import profile_and_compile

        f = jax.jit(lambda x: x * 2.0)
        x = jnp.arange(4, dtype=jnp.float32)
        compiled, m = profile_and_compile(f, x, label="unit")
        assert m["flops"] > 0
        np.testing.assert_allclose(np.asarray(compiled(x)), np.arange(4) * 2.0)
        # Non-jitted callables pass through untouched.
        fn, m2 = profile_and_compile(lambda x: x, x, label="plain")
        assert m2 == {} and fn(1) == 1

    def test_kill_switch(self, monkeypatch):
        from p2pmicrogrid_tpu.telemetry import profiling_enabled

        monkeypatch.setenv("P2P_PROFILE", "0")
        assert not profiling_enabled()
        monkeypatch.setenv("P2P_PROFILE", "1")
        assert profiling_enabled()

    def test_train_community_profiles_episode_scan(self, tmp_path):
        """Acceptance: HLO flops + peak-memory gauges appear for the
        episode scan of a telemetry-attached training run."""
        from p2pmicrogrid_tpu.data import synthetic_traces
        from p2pmicrogrid_tpu.envs import make_ratings
        from p2pmicrogrid_tpu.train import (
            init_policy_state,
            make_policy,
            train_community,
        )

        cfg = default_config(
            sim=SimConfig(n_agents=2),
            train=TrainConfig(implementation="tabular", max_episodes=2),
        )
        traces = synthetic_traces(n_days=1, start_day=11).normalized()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        tel = Telemetry.create("profile-train", cfg=cfg, root=str(tmp_path))
        train_community(
            cfg, policy, ps, traces, ratings, jax.random.PRNGKey(0),
            telemetry=tel,
        )
        tel.close()
        summary = json.load(open(os.path.join(tel.run_dir, "summary.json")))
        g = summary["gauges"]
        assert g["profile.episode_scan.flops"] > 0
        assert g["profile.episode_scan.peak_bytes"] > 0


class TestReportDegradation:
    def test_truncated_jsonl_line_skipped_with_warning(self, tmp_path):
        from p2pmicrogrid_tpu.telemetry.report import load_run, render_run

        tel = Telemetry.create("trunc", root=str(tmp_path))
        tel.event("health", episode=0, greedy_cost_eur=1.0,
                  greedy_reward=-1.0, status="healthy")
        tel.close()
        # Simulate a run killed mid-write: a truncated trailing line.
        with open(os.path.join(tel.run_dir, "metrics.jsonl"), "a") as f:
            f.write('{"ts": 1.0, "kind": "hea')
        data = load_run(tel.run_dir)
        assert any("truncated" in w for w in data["warnings"])
        # The valid events still load; the render carries the warning.
        assert any(e["kind"] == "health" for e in data["events"])
        text = render_run(tel.run_dir)
        assert "WARNING" in text and "health" in text

    def test_empty_run_dir_renders(self, tmp_path):
        from p2pmicrogrid_tpu.telemetry.report import render_run

        run = tmp_path / "empty-run"
        run.mkdir()
        text = render_run(str(run))
        assert "no manifest.json" in text

    def test_corrupt_manifest_and_summary_warn(self, tmp_path):
        from p2pmicrogrid_tpu.telemetry.report import load_run

        run = tmp_path / "bad-run"
        run.mkdir()
        (run / "manifest.json").write_text("{not json")
        (run / "summary.json").write_text("")
        data = load_run(str(run))
        assert data["manifest"] is None and data["summary"] is None
        assert len(data["warnings"]) == 2

    def test_cli_report_survives_partial_run(self, tmp_path, capsys):
        from p2pmicrogrid_tpu.cli import main

        run = tmp_path / "partial"
        run.mkdir()
        (run / "metrics.jsonl").write_text('{"ts": 1.0, "kind": "x"}\n{"tr')
        assert main(["telemetry-report", str(run)]) == 0
        out = capsys.readouterr().out
        assert "WARNING" in out

    def test_compare_with_partial_run(self, tmp_path):
        from p2pmicrogrid_tpu.telemetry.report import compare_runs

        tel = Telemetry.create("ok", root=str(tmp_path))
        tel.counter("c", 1)
        tel.close()
        partial = tmp_path / "partial"
        partial.mkdir()
        (partial / "manifest.json").write_text("{broken")
        text = compare_runs(tel.run_dir, str(partial))
        assert "WARNING (B)" in text


class TestReport:
    def test_render_run_smoke(self, tmp_path):
        tel = Telemetry.create("report-test", root=str(tmp_path))
        tel.event("health", episode=0, greedy_cost_eur=12.0,
                  greedy_reward=-2.0, status="healthy")
        tel.event("basin_alert", episode=10, greedy_cost_eur=-400.0,
                  greedy_reward=-1500.0)
        tel.counter("device.comfort_violations", 7)
        tel.histogram("serve.batch_ms", 1.5)
        tel.histogram("serve.batch_ms", 2.5)
        with tel.span("train_block"):
            pass
        tel.close()
        from p2pmicrogrid_tpu.telemetry.report import latest_run_dir, render_run

        assert latest_run_dir(str(tmp_path)) == tel.run_dir
        text = render_run(tel.run_dir)
        assert "manifest" in text
        assert "BASIN ALERTS" in text and "10" in text
        assert "device.comfort_violations" in text
        assert "serve.batch_ms" in text  # histogram stats render too
        assert "train_block" in text

    def test_cli_telemetry_report(self, tmp_path, capsys):
        tel = Telemetry.create("cli-test", root=str(tmp_path))
        tel.event("health", episode=0, greedy_cost_eur=1.0,
                  greedy_reward=-1.0, status="healthy")
        tel.close()
        from p2pmicrogrid_tpu.cli import main

        assert main(["telemetry-report", tel.run_dir]) == 0
        out = capsys.readouterr().out
        assert tel.run_id in out and "health" in out
        assert main(["telemetry-report", str(tmp_path / "nope")]) == 1


class TestServeCompaction:
    """Warehouse retention (ISSUE 5 satellite): per-request serve_request
    rows older than the window roll into per-(run, bucket) aggregates, so
    a long-running gateway's telemetry stays bounded."""

    def _seed_serve_requests(self, db, run_id="gw-run", config_hash="cfg-a"):
        """A warehouse with 12 serve_request points across two buckets,
        all stamped 2 hours in the past, plus one fresh point."""
        import time as _time

        from p2pmicrogrid_tpu.telemetry import SqliteSink

        sink = SqliteSink(db, batch=1)
        sink.register_run(run_id, {"config_hash": config_hash, "created": "t"})
        old = _time.time() - 2 * 3600
        waits = []
        for i in range(12):
            bucket = 4 if i % 2 else 1
            wait = float(i)
            waits.append((bucket, wait))
            sink.emit({
                "ts": old + i, "kind": "serve_request", "source": "queue",
                "bucket": bucket, "batch_size": 1,
                "padded_rows": bucket - 1, "wait_ms": wait,
                "service_ms": 2.0, "latency_ms": wait + 2.0,
            })
        sink.emit({
            "ts": _time.time(), "kind": "serve_request", "source": "queue",
            "bucket": 2, "batch_size": 2, "padded_rows": 0,
            "wait_ms": 0.5, "service_ms": 1.0, "latency_ms": 1.5,
        })
        sink.close()
        return waits

    def test_round_trip(self, tmp_path):
        from p2pmicrogrid_tpu.data.results import ResultsStore

        db = str(tmp_path / "r.db")
        self._seed_serve_requests(db)
        with ResultsStore(db) as store:
            summary = store.compact_serve_telemetry(older_than_hours=1.0)
            assert summary.items() >= {
                "rows_compacted": 12, "aggregates_written": 2,
                "decisions_compacted": 0, "lease_capped": False,
            }.items()
            # The recent row survives raw; the old tail is aggregates now.
            (raw,) = store.con.execute(
                "SELECT COUNT(*) FROM telemetry_points "
                "WHERE kind='serve_request'"
            ).fetchone()
            assert raw == 1
            aggs = store.con.execute(
                "SELECT name, value, attrs_json FROM telemetry_points "
                "WHERE kind='serve_request_agg' ORDER BY name"
            ).fetchall()
            assert [a[0] for a in aggs] == ["bucket_1", "bucket_4"]
            # Request counts are preserved exactly across the roll-up.
            assert sum(int(a[1]) for a in aggs) == 12
            attrs = json.loads(aggs[1][2])
            assert attrs["bucket"] == 4
            assert attrs["requests"] == 6
            assert attrs["padded_rows"] == 6 * 3
            odd_waits = np.asarray([1.0, 3.0, 5.0, 7.0, 9.0, 11.0])
            assert attrs["wait_ms"]["p95"] == pytest.approx(
                float(np.percentile(odd_waits, 95)), abs=1e-3
            )
            assert attrs["ts_min"] < attrs["ts_max"]
            # Idempotent: a second pass finds nothing left to compact.
            assert store.compact_serve_telemetry(
                older_than_hours=1.0
            ).items() >= {
                "rows_compacted": 0, "aggregates_written": 0,
                "decisions_compacted": 0,
            }.items()
            # The warehouse stays orphan-free (seq continuity preserved).
            (orphans,) = store.con.execute(
                "SELECT COUNT(*) FROM telemetry_points t WHERE NOT EXISTS "
                "(SELECT 1 FROM telemetry_runs r WHERE r.run_id = t.run_id)"
            ).fetchone()
            assert orphans == 0

    def test_compact_while_sink_is_live(self, tmp_path):
        """The stated use case is compacting a LONG-RUNNING gateway's
        warehouse: a live SqliteSink's in-memory seq counter must not
        collide with the aggregate rows' seqs (a collision makes the
        sink's next batch fail its PRIMARY KEY and silently drop
        telemetry from then on)."""
        import time as _time

        from p2pmicrogrid_tpu.data.results import ResultsStore
        from p2pmicrogrid_tpu.telemetry import SqliteSink

        db = str(tmp_path / "r.db")
        sink = SqliteSink(db, batch=1)
        sink.register_run("live-run", {"config_hash": "cfg", "created": "t"})
        old = _time.time() - 2 * 3600
        for i in range(4):
            sink.emit({"ts": old + i, "kind": "serve_request", "bucket": 1,
                       "wait_ms": 1.0, "service_ms": 1.0, "latency_ms": 2.0})
        # Compact mid-run, sink still open and counting in memory.
        with ResultsStore(db) as store:
            assert store.compact_serve_telemetry(
                older_than_hours=1.0
            ).items() >= {
                "rows_compacted": 4, "aggregates_written": 1,
                "decisions_compacted": 0,
            }.items()
        for i in range(4):  # the live sink keeps streaming afterwards
            sink.emit({"ts": _time.time(), "kind": "serve_request",
                       "bucket": 2, "wait_ms": 1.0, "service_ms": 1.0,
                       "latency_ms": 2.0})
        sink.close()
        with ResultsStore(db) as store:
            (raw,) = store.con.execute(
                "SELECT COUNT(*) FROM telemetry_points "
                "WHERE kind='serve_request'"
            ).fetchone()
            assert raw == 4  # nothing silently dropped post-compaction
            # Second pass still finds and rolls the new tail eventually.
            summary = store.compact_serve_telemetry(
                older_than_hours=0.0, now=_time.time() + 1
            )
            assert summary["rows_compacted"] == 4

    def test_cli_compact(self, tmp_path, capsys):
        from p2pmicrogrid_tpu.cli import main

        db = str(tmp_path / "r.db")
        self._seed_serve_requests(db)
        rc = main([
            "telemetry-query", "--results-db", db, "--compact",
            "--older-than-hours", "1",
        ])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out.strip())
        assert doc["compacted"]["rows_compacted"] == 12
        assert doc["compacted"]["aggregates_written"] == 2
        # Re-running is a no-op, and a missing DB fails loud.
        assert main([
            "telemetry-query", "--results-db", db, "--compact",
        ]) == 0
        assert main([
            "telemetry-query", "--results-db", str(tmp_path / "nope.db"),
            "--compact",
        ]) == 1


class TestIngestLagGauge:
    def test_flush_records_sink_gauge_and_fleet_view_surfaces_it(
        self, tmp_path
    ):
        """Every SqliteSink flush records the oldest buffered event's
        commit lag as ``telemetry.ingest_lag_ms`` — kind ``sink_gauge``,
        NOT ``gauge``, so sink-internal health never inflates a run's
        user-gauge counts — and the fleet view surfaces the worst lag
        per config."""
        import sqlite3

        from p2pmicrogrid_tpu.data import ResultsStore
        from p2pmicrogrid_tpu.telemetry import (
            SqliteSink,
            Telemetry,
            run_manifest,
        )

        db = str(tmp_path / "results.db")
        tel = Telemetry(
            run_id="lag-test",
            sinks=[SqliteSink(db)],
            manifest=run_manifest(
                extra={"config_hash": "cfg-lag", "serve_role": "router"}
            ),
        )
        tel.gauge("user.gauge", 1.0)
        tel.event("noise")
        tel.close()

        con = sqlite3.connect(db)
        try:
            rows = con.execute(
                "SELECT kind, name, value FROM telemetry_points "
                "WHERE kind IN ('gauge', 'sink_gauge')"
            ).fetchall()
        finally:
            con.close()
        lags = [r for r in rows if r[0] == "sink_gauge"]
        assert lags and all(
            r[1] == "telemetry.ingest_lag_ms" and r[2] >= 0.0 for r in lags
        )
        # The user-gauge count is untouched by the sink's own point.
        assert sum(1 for r in rows if r[0] == "gauge") == 1

        store = ResultsStore(db)
        try:
            fleet = store.query_fleet_view()
        finally:
            store.close()
        assert len(fleet) == 1
        assert fleet[0]["ingest_lag_ms"] is not None
        assert fleet[0]["ingest_lag_ms"] >= 0.0
