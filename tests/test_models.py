"""Model-layer tests: batched tabular Q, replay ring, DQN, DDPG.

Oracles follow SURVEY.md section 4: closed-form pieces are checked against
hand-computed values; batched/vmapped paths are checked against a sequential
NumPy re-derivation of the reference semantics (rl.py:89-129).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import DDPGConfig, DQNConfig, QLearningConfig
from p2pmicrogrid_tpu.models import (
    ddpg_act,
    ddpg_init,
    ddpg_update,
    dqn_act,
    dqn_decay,
    dqn_init,
    dqn_initialize_target,
    dqn_update,
    replay_add,
    replay_init,
    replay_sample,
    tabular_act,
    tabular_decay,
    tabular_init,
    tabular_update,
)
from p2pmicrogrid_tpu.ops.obs import discretize


class TestTabular:
    def test_init_shape(self):
        cfg = QLearningConfig()
        st = tabular_init(cfg, n_agents=3)
        assert st.q_table.shape == (3, 20, 20, 20, 20, 3)
        assert float(st.epsilon) == pytest.approx(0.81)

    def test_greedy_action_picks_argmax(self):
        cfg = QLearningConfig()
        st = tabular_init(cfg, n_agents=2)
        obs = jnp.array([[0.5, 0.0, 0.0, 0.0], [0.5, 0.0, 0.0, 0.0]])
        ti, tpi, bi, pi = discretize(cfg, obs)
        # Plant a known best action per agent.
        q = st.q_table
        q = q.at[0, ti[0], tpi[0], bi[0], pi[0], 2].set(5.0)
        q = q.at[1, ti[1], tpi[1], bi[1], pi[1], 1].set(7.0)
        st = st._replace(q_table=q)

        action, qv = tabular_act(cfg, st, obs, jax.random.PRNGKey(0), explore=False)
        assert action.tolist() == [2, 1]
        assert qv.tolist() == [5.0, 7.0]

    def test_bellman_update_matches_hand_value(self):
        # One agent, alpha/gamma made large so the delta is visible.
        cfg = QLearningConfig(alpha=0.5, gamma=0.9)
        st = tabular_init(cfg, n_agents=1)
        obs = jnp.array([[0.0, 0.0, 0.0, 0.0]])
        next_obs = jnp.array([[0.99, 0.0, 0.0, 0.0]])
        ti, tpi, bi, pi = discretize(cfg, obs)
        nti, ntpi, nbi, npi = discretize(cfg, next_obs)

        q = st.q_table.at[0, nti[0], ntpi[0], nbi[0], npi[0], 1].set(2.0)
        st = st._replace(q_table=q)

        st2 = tabular_update(
            cfg, st, obs, jnp.array([0]), jnp.array([-1.0]), next_obs
        )
        # q <- 0 + 0.5 * (-1 + 0.9*2 - 0) = 0.4
        got = st2.q_table[0, ti[0], tpi[0], bi[0], pi[0], 0]
        assert float(got) == pytest.approx(0.4)

    def test_update_is_per_agent_isolated(self):
        cfg = QLearningConfig(alpha=1.0)
        st = tabular_init(cfg, n_agents=2)
        obs = jnp.zeros((2, 4))
        st2 = tabular_update(
            cfg, st, obs, jnp.array([0, 0]), jnp.array([1.0, 0.0]), obs
        )
        # Agent 1 had zero reward and zero table: no change anywhere in its table.
        assert float(jnp.abs(st2.q_table[1]).max()) == 0.0
        assert float(jnp.abs(st2.q_table[0]).max()) > 0.0

    def test_epsilon_decay_floor(self):
        cfg = QLearningConfig()
        st = tabular_init(cfg, 1)._replace(epsilon=jnp.asarray(0.105))
        st = tabular_decay(cfg, st)
        assert float(st.epsilon) == pytest.approx(0.1)  # floor (rl.py:132)

    @pytest.mark.slow
    def test_explore_rate_statistical(self):
        cfg = QLearningConfig()
        st = tabular_init(cfg, n_agents=1000)._replace(epsilon=jnp.asarray(0.5))
        obs = jnp.zeros((1000, 4))
        # All-zero tables: greedy is action 0; explored slots uniform over 3.
        action, _ = tabular_act(cfg, st, obs, jax.random.PRNGKey(1), explore=True)
        frac_nonzero = float(jnp.mean(action != 0))
        # P(action != 0) = eps * 2/3 = 1/3.
        assert 0.25 < frac_nonzero < 0.42


class TestReplay:
    def test_ring_wraps(self):
        st = replay_init(n_agents=2, capacity=3)
        for i in range(5):
            st = replay_add(
                st,
                jnp.full((2, 4), float(i)),
                jnp.full((2, 1), float(i)),
                jnp.full((2,), float(i)),
                jnp.full((2, 4), float(i + 10)),
            )
        assert int(st.count) == 3
        assert int(st.cursor) == 2  # 5 mod 3
        # Slot 0 and 1 hold the two newest writes (3, 4); slot 2 holds 2.
        assert st.reward[:, 0].tolist() == [3.0, 3.0]
        assert st.reward[:, 1].tolist() == [4.0, 4.0]
        assert st.reward[:, 2].tolist() == [2.0, 2.0]

    def test_sample_only_filled_region(self):
        st = replay_init(n_agents=1, capacity=100)
        for i in range(4):
            st = replay_add(
                st,
                jnp.zeros((1, 4)),
                jnp.zeros((1, 1)),
                jnp.full((1,), float(i + 1)),
                jnp.zeros((1, 4)),
            )
        _, _, r, _ = replay_sample(st, jax.random.PRNGKey(0), batch_size=64)
        assert float(r.min()) >= 1.0  # never samples the zeroed tail

    def test_sample_shapes(self):
        st = replay_init(n_agents=3, capacity=10)
        st = replay_add(
            st, jnp.zeros((3, 4)), jnp.zeros((3, 1)), jnp.zeros((3,)), jnp.zeros((3, 4))
        )
        s, a, r, ns = replay_sample(st, jax.random.PRNGKey(0), batch_size=8)
        assert s.shape == (3, 8, 4)
        assert a.shape == (3, 8, 1)
        assert r.shape == (3, 8)
        assert ns.shape == (3, 8, 4)


class TestDQN:
    def setup_method(self):
        self.cfg = DQNConfig(buffer_size=64, batch_size=8)
        self.st = dqn_init(self.cfg, n_agents=2, key=jax.random.PRNGKey(0))

    def test_init_epsilon_is_one(self):
        # agent.py:304 — ActorModel(1), not the 0.1 class default.
        assert float(self.st.epsilon) == 1.0

    def test_act_shapes_and_range(self):
        obs = jnp.zeros((2, 4))
        action, q = dqn_act(self.cfg, self.st, obs, jax.random.PRNGKey(1), explore=False)
        assert action.shape == (2,)
        assert q.shape == (2,)
        assert set(np.asarray(action).tolist()) <= {0, 1, 2}

    def test_agents_have_independent_params(self):
        k0 = self.st.online["Dense_0"]["kernel"]
        assert not np.allclose(np.asarray(k0[0]), np.asarray(k0[1]))

    @pytest.mark.slow
    def test_update_moves_online_and_target(self):
        obs = jnp.ones((2, 4)) * 0.1
        st2, loss = dqn_update(
            self.cfg,
            self.st,
            obs,
            jnp.array([1, 2]),
            jnp.array([-1.0, -2.0]),
            obs,
            jax.random.PRNGKey(2),
        )
        assert loss.shape == (2,)
        d_on = np.abs(
            np.asarray(st2.online["Dense_0"]["kernel"])
            - np.asarray(self.st.online["Dense_0"]["kernel"])
        ).max()
        assert d_on > 0
        # Polyak pulls target toward online by factor tau.
        gap_before = np.abs(
            np.asarray(self.st.target["Dense_0"]["kernel"])
            - np.asarray(self.st.online["Dense_0"]["kernel"])
        ).mean()
        gap_after = np.abs(
            np.asarray(st2.target["Dense_0"]["kernel"])
            - np.asarray(st2.online["Dense_0"]["kernel"])
        ).mean()
        assert gap_after < gap_before

    def test_initialize_target_hard_copy(self):
        st2 = dqn_initialize_target(self.st)
        np.testing.assert_allclose(
            np.asarray(st2.target["Dense_0"]["kernel"]),
            np.asarray(st2.online["Dense_0"]["kernel"]),
        )

    def test_decay_no_floor(self):
        st = self.st._replace(epsilon=jnp.asarray(0.01))
        st = dqn_decay(self.cfg, st)
        assert float(st.epsilon) == pytest.approx(0.009)

    def test_update_jits(self):
        obs = jnp.zeros((2, 4))
        f = jax.jit(
            lambda st, k: dqn_update(
                self.cfg, st, obs, jnp.array([0, 1]), jnp.array([0.0, 0.0]), obs, k
            )
        )
        st2, _ = f(self.st, jax.random.PRNGKey(3))
        assert int(st2.replay.count) == 1


class TestDDPG:
    def setup_method(self):
        self.cfg = DDPGConfig(buffer_size=64, batch_size=8)
        self.st = ddpg_init(self.cfg, n_agents=2, key=jax.random.PRNGKey(0))

    def test_act_in_unit_interval(self):
        obs = jnp.zeros((2, 4))
        a, q, st = ddpg_act(self.cfg, self.st, obs, jax.random.PRNGKey(1))
        assert a.shape == (2,)
        assert float(a.min()) >= 0.0
        assert float(a.max()) <= 1.0

    def test_ou_noise_evolves(self):
        obs = jnp.zeros((2, 4))
        _, _, st = ddpg_act(self.cfg, self.st, obs, jax.random.PRNGKey(1))
        assert not np.allclose(np.asarray(st.ou_state), np.asarray(self.st.ou_state))

    def test_ou_init_uses_configured_sd(self):
        # rl_backup.py:81,102 — x0 ~ N(0, ou_init_sd), not zeros.
        assert not np.allclose(np.asarray(self.st.ou_state), 0.0)

    def test_greedy_does_not_touch_noise(self):
        obs = jnp.zeros((2, 4))
        _, _, st = ddpg_act(self.cfg, self.st, obs, jax.random.PRNGKey(1), explore=False)
        np.testing.assert_allclose(
            np.asarray(st.ou_state), np.asarray(self.st.ou_state)
        )

    def test_noise_annealing_optin(self):
        """noise_decay=1.0 (default) keeps exploration stationary — validated
        empirically round 2: annealing HURTS this task (the actor over-
        exploits the imperfect critic without fresh exploration data) — but
        the knob must work when opted into."""
        from p2pmicrogrid_tpu.models import ddpg_decay

        st = ddpg_decay(self.cfg, self.st)  # default decay 1.0
        assert float(st.noise_scale) == 1.0
        cfg2 = DDPGConfig(buffer_size=64, batch_size=8, noise_decay=0.9)
        st = ddpg_decay(cfg2, self.st)
        assert abs(float(st.noise_scale) - 0.9) < 1e-6
        # The annealed scale shrinks the exploration perturbation.
        obs = jnp.zeros((2, 4))
        st_small = st._replace(noise_scale=jnp.asarray(0.0))
        a_greedy, _, _ = ddpg_act(self.cfg, self.st, obs, jax.random.PRNGKey(1), explore=False)
        a_zeroed, _, _ = ddpg_act(cfg2, st_small, obs, jax.random.PRNGKey(1), explore=True)
        np.testing.assert_allclose(np.asarray(a_zeroed), np.clip(np.asarray(a_greedy), 0, 1), atol=1e-6)

    def test_update_moves_both_nets(self):
        obs = jnp.ones((2, 4)) * 0.2
        st2, loss = ddpg_update(
            self.cfg,
            self.st,
            obs,
            jnp.array([0.3, 0.7]),
            jnp.array([-1.0, -0.5]),
            obs,
            jax.random.PRNGKey(2),
        )
        assert loss.shape == (2,)
        for name, old, new in [
            ("actor", self.st.actor, st2.actor),
            ("critic", self.st.critic, st2.critic),
        ]:
            delta = np.abs(
                np.asarray(new["Dense_0"]["kernel"]) - np.asarray(old["Dense_0"]["kernel"])
            ).max()
            assert delta > 0, name


class TestRecurrentDDPG:
    """The reference's stale LSTM iteration, architecture-faithful
    (rl_backup.py:14-62): shared-weights double-LSTM trunk, sigmoid actor
    head, sequence-summed critic head, episodic DDPG step."""

    def _cfg(self):
        return DDPGConfig(actor_lr=1e-3, critic_lr=1e-3)

    def test_shapes_and_ranges(self):
        from p2pmicrogrid_tpu.models import (
            recurrent_ddpg_act,
            recurrent_ddpg_init,
        )

        st = recurrent_ddpg_init(self._cfg(), jax.random.PRNGKey(0), seq_len=8)
        obs = jax.random.uniform(jax.random.PRNGKey(1), (3, 8, 4))
        a = recurrent_ddpg_act(self._cfg(), st, obs)
        assert a.shape == (3, 8, 1)
        assert float(a.min()) >= 0.0 and float(a.max()) <= 1.0
        # OU-noised action stays clipped.
        ou = 10.0 * jnp.ones((3, 8, 1))
        an = recurrent_ddpg_act(self._cfg(), st, obs, ou)
        assert float(an.max()) <= 1.0

    def test_lstm_weights_shared_across_double_pass(self):
        """The Keras model lists self.lstm twice — ONE weight set does two
        passes. The param tree must contain exactly one RNN scope per net."""
        from p2pmicrogrid_tpu.models import recurrent_ddpg_init

        st = recurrent_ddpg_init(self._cfg(), jax.random.PRNGKey(0), seq_len=8)
        rnn_scopes = [k for k in st.actor if "RNN" in k or "LSTM" in k]
        assert len(rnn_scopes) == 1, st.actor.keys()

    @pytest.mark.slow
    def test_learn_step_reduces_critic_loss(self):
        from p2pmicrogrid_tpu.models import (
            recurrent_ddpg_init,
            recurrent_ddpg_learn,
        )

        cfg = self._cfg()
        st = recurrent_ddpg_init(cfg, jax.random.PRNGKey(0), seq_len=8)
        k = jax.random.PRNGKey(1)
        obs = jax.random.uniform(k, (16, 8, 4))
        act = jax.random.uniform(jax.random.fold_in(k, 1), (16, 8, 1))
        rew = jax.random.uniform(jax.random.fold_in(k, 2), (16,))
        nobs = jax.random.uniform(jax.random.fold_in(k, 3), (16, 8, 4))
        learn = jax.jit(lambda s: recurrent_ddpg_learn(cfg, s, obs, act, rew, nobs))
        _, first = learn(st)
        for _ in range(30):
            st, loss = learn(st)
        assert float(loss) < float(first)
        assert np.isfinite(float(loss))
