"""Matrix-free factored market clearing (ops/factored_market.py): exact
equivalence with the reference-semantics matrix chain
(divide_power -> clear_market, microgrid/community.py:45-54 + agent.py:186-195)
for the one-round negotiation whose rank-1 structure it exploits."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    BatteryConfig,
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.ops.factored_market import (
    clear_factored_rounds0,
    clear_factored_rounds1,
    rank1_min_sums,
)
from p2pmicrogrid_tpu.ops.market import (
    clear_market,
    divide_power,
    zero_diagonal,
)


# Whole module is compile-heavy (episode-level factored/matrix equivalence).
pytestmark = pytest.mark.slow

def matrix_chain(b0, b1):
    """The matrix-path computation the factored clearing must reproduce:
    equal-split round 0 (divide_power against a zero matrix), one
    proportional divide, pairwise sign-opposition clearing."""
    S, A = b0.shape
    P0 = jnp.broadcast_to((b0 / A)[..., None], (S, A, A))
    powers = -jnp.swapaxes(zero_diagonal(P0), -1, -2)
    P1 = divide_power(b1, powers)
    return clear_market(P1)


def assert_clear_equiv(b0, b1):
    g1, p1 = matrix_chain(jnp.asarray(b0), jnp.asarray(b1))
    g2, p2 = clear_factored_rounds1(jnp.asarray(b0), jnp.asarray(b1))
    scale = max(1.0, float(np.abs(np.asarray(p1)).max()))
    np.testing.assert_allclose(
        np.asarray(p2), np.asarray(p1), rtol=1e-4, atol=2e-4 * scale
    )
    np.testing.assert_allclose(
        np.asarray(g2), np.asarray(g1), rtol=1e-4, atol=2e-4 * scale
    )


class TestRank1MinSums:
    def test_matches_brute_force(self):
        rng = np.random.default_rng(0)
        for _ in range(20):
            a, d, b, g = (
                jnp.asarray(
                    np.abs(rng.normal(0, 2, (2, 9))).astype(np.float32)
                )
                for _ in range(4)
            )
            m = jnp.minimum(
                a[..., :, None] * b[..., None, :],
                d[..., :, None] * g[..., None, :],
            )
            row, col = rank1_min_sums(a, d, b, g)
            np.testing.assert_allclose(row, m.sum(-1), rtol=1e-6)
            np.testing.assert_allclose(col, m.sum(-2), rtol=1e-6)

    def test_zero_weights_contribute_nothing(self):
        a = jnp.asarray([[1.0, 0.0, 2.0]])
        d = jnp.asarray([[1.0, 5.0, 0.0]])
        b = jnp.asarray([[0.0, 3.0, 1.0]])
        g = jnp.asarray([[4.0, 0.0, 1.0]])
        row, col = rank1_min_sums(a, d, b, g)
        # i=1: a=0 -> min(0, ...) = 0 everywhere except where gamma>0 gives
        # min(0, d*g) = 0 too; all contributions zero.
        m = jnp.minimum(
            a[..., :, None] * b[..., None, :],
            d[..., :, None] * g[..., None, :],
        )
        np.testing.assert_allclose(row, m.sum(-1), rtol=1e-6)
        np.testing.assert_allclose(col, m.sum(-2), rtol=1e-6)


class TestClearEquivalence:
    """Randomized + adversarial equivalence vs the matrix chain, covering
    every branch: proportional and equal divide rows, one-sided markets,
    zero balances, and the equal-row diagonal residue."""

    @pytest.mark.parametrize("a_agents", [2, 3, 17, 100])
    def test_randomized(self, a_agents):
        rng = np.random.default_rng(a_agents)
        for trial in range(24):
            b0 = rng.normal(0, 1000, (2, a_agents)).astype(np.float32)
            b1 = rng.normal(0, 1000, (2, a_agents)).astype(np.float32)
            style = trial % 8
            if style == 1:
                b0 = np.abs(b0)          # one-sided round 0
            if style == 2:
                b1 = np.abs(b1)          # all buyers -> nothing matches
            if style == 3:
                b0 = -np.abs(b0)
            if style == 4:
                b0[:, : a_agents // 2] = 0.0
            if style == 5:
                b1[:, ::2] = 0.0         # zero rows
            if style == 6:
                b0[:] = 0.0              # every row takes the equal branch
            if style == 7:
                b0 = np.abs(b0)
                b1 = np.abs(b1)
                b1[:, 0] = -b1[:, 0]     # single seller
            assert_clear_equiv(b0, b1)

    def test_all_buyers_nothing_matches(self):
        b0 = np.abs(np.random.default_rng(0).normal(0, 100, (1, 5))).astype(
            np.float32
        )
        b1 = np.abs(np.random.default_rng(1).normal(0, 100, (1, 5))).astype(
            np.float32
        )
        g, p = clear_factored_rounds1(jnp.asarray(b0), jnp.asarray(b1))
        np.testing.assert_allclose(np.asarray(p), 0.0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(g), b1, rtol=1e-6)

    def test_power_balance_invariants(self):
        """Row sums telescope: grid + p2p = b1 exactly, and matched p2p
        power nets to ~zero across the community."""
        rng = np.random.default_rng(7)
        b0 = rng.normal(0, 1000, (3, 40)).astype(np.float32)
        b1 = rng.normal(0, 1000, (3, 40)).astype(np.float32)
        g, p = clear_factored_rounds1(jnp.asarray(b0), jnp.asarray(b1))
        np.testing.assert_allclose(np.asarray(g + p), b1, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(p).sum(-1), 0.0, atol=1e-2
        )  # buyers' matched power == sellers'

    def test_rounds0_equivalence(self):
        rng = np.random.default_rng(3)
        for trial in range(10):
            b0 = rng.normal(0, 1000, (2, 11)).astype(np.float32)
            if trial % 3 == 1:
                b0[:, ::2] = 0.0
            A = b0.shape[-1]
            P = jnp.broadcast_to(
                (jnp.asarray(b0) / A)[..., None], (2, A, A)
            )
            g1, p1 = clear_market(P)
            g2, p2 = clear_factored_rounds0(jnp.asarray(b0))
            scale = max(1.0, float(np.abs(np.asarray(p1)).max()))
            np.testing.assert_allclose(
                np.asarray(p2), np.asarray(p1), rtol=1e-4, atol=2e-4 * scale
            )
            np.testing.assert_allclose(
                np.asarray(g2), np.asarray(g1), rtol=1e-4, atol=2e-4 * scale
            )


class TestSlotIntegration:
    """market_impl='factored' must reproduce the matrix path through full
    training episodes (same keys -> same decisions; only clearing
    arithmetic differs)."""

    def _run(self, impl, rounds):
        from p2pmicrogrid_tpu.envs import make_ratings
        from p2pmicrogrid_tpu.parallel import (
            init_shared_state,
            stack_scenario_arrays,
        )
        from p2pmicrogrid_tpu.parallel.scenarios import (
            make_scenario_traces,
            train_scenarios_shared,
        )
        from p2pmicrogrid_tpu.train import make_policy

        cfg = default_config(
            sim=SimConfig(
                n_agents=7, n_scenarios=3, rounds=rounds, market_impl=impl
            ),
            battery=BatteryConfig(enabled=True),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(
                buffer_size=16, batch_size=2, share_across_agents=True
            ),
        )
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        traces = make_scenario_traces(cfg, 3)
        arrays = stack_scenario_arrays(cfg, traces, ratings)
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
        out, _, rew, loss, _ = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(1),
            n_episodes=2, replay_s=scen,
        )
        return out, np.asarray(rew), np.asarray(loss)

    @pytest.mark.parametrize("rounds", [0, 1])
    def test_episode_equivalence(self, rounds):
        om, rm, lm = self._run("matrix", rounds)
        of, rf, lf = self._run("factored", rounds)
        np.testing.assert_allclose(rf, rm, rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(lf, lm, rtol=1e-3, atol=1e-3)
        fm_ = np.concatenate(
            [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(om)]
        )
        ff = np.concatenate(
            [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(of)]
        )
        np.testing.assert_allclose(ff, fm_, rtol=1e-3, atol=1e-4)


class TestConfigValidation:
    def test_factored_rejects_multi_round(self):
        with pytest.raises(ValueError, match="rounds <= 1"):
            SimConfig(rounds=2, market_impl="factored")

    def test_bad_impl_rejected(self):
        with pytest.raises(ValueError, match="market_impl"):
            SimConfig(market_impl="magic")

    def test_auto_resolution(self):
        from p2pmicrogrid_tpu.envs.community import resolve_market_impl

        # On the CPU test backend, auto must stay on the matrix path so
        # committed CPU-measured artifacts remain bit-identical.
        cfg = default_config(sim=SimConfig(n_agents=5, n_scenarios=2))
        assert resolve_market_impl(cfg) == "matrix"
        forced = default_config(
            sim=SimConfig(n_agents=5, n_scenarios=2, market_impl="factored")
        )
        assert resolve_market_impl(forced) == "factored"
        multi_round = default_config(
            sim=SimConfig(n_agents=5, rounds=2, use_pallas=True)
        )
        assert resolve_market_impl(multi_round) == "matrix"


    def test_bf16_factored_episode_close_to_f32(self):
        """Explicit market_dtype='bfloat16' + factored now carries the fused
        min pass in bf16 (community.py wires resolve_market_dtype through);
        episode rewards must stay within the same tolerance class as the
        bf16 matrix storage (test_pallas.py's 2%)."""
        from p2pmicrogrid_tpu.envs import make_ratings
        from p2pmicrogrid_tpu.parallel import (
            init_shared_state,
            stack_scenario_arrays,
        )
        from p2pmicrogrid_tpu.parallel.scenarios import (
            make_scenario_traces,
            train_scenarios_shared,
        )
        from p2pmicrogrid_tpu.train import make_policy

        def run(dtype):
            cfg = default_config(
                sim=SimConfig(
                    n_agents=7, n_scenarios=3, market_impl="factored",
                    market_dtype=dtype,
                ),
                battery=BatteryConfig(enabled=True),
                train=TrainConfig(implementation="ddpg"),
                ddpg=DDPGConfig(
                    buffer_size=16, batch_size=2, share_across_agents=True
                ),
            )
            ratings = make_ratings(cfg, np.random.default_rng(0))
            policy = make_policy(cfg)
            traces = make_scenario_traces(cfg, 3)
            arrays = stack_scenario_arrays(cfg, traces, ratings)
            ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
            _, _, rew, _, _ = train_scenarios_shared(
                cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(1),
                n_episodes=2, replay_s=scen,
            )
            return np.asarray(rew)

        r32, r16 = run("float32"), run("bfloat16")
        scale = np.abs(r32).max()
        np.testing.assert_allclose(r16, r32, atol=0.02 * scale)


class TestBf16Compute:
    def test_bf16_min_pass_close_to_f32(self):
        """compute_dtype=bfloat16 carries the O(A^2) min pass in bf16 with
        f32 accumulation — the factored counterpart of market_dtype
        'bfloat16' storage, same tolerance class (community.py:417-436)."""
        import jax.numpy as jnp

        from p2pmicrogrid_tpu.ops.factored_market import clear_factored_rounds1

        k = jax.random.PRNGKey(3)
        b0 = jax.random.normal(k, (4, 200)) * 1500.0
        b1 = jax.random.normal(jax.random.fold_in(k, 1), (4, 200)) * 1500.0
        g32, p32 = clear_factored_rounds1(b0, b1)
        g16, p16 = clear_factored_rounds1(b0, b1, compute_dtype=jnp.bfloat16)
        assert g16.dtype == jnp.float32 and p16.dtype == jnp.float32
        scale = float(jnp.abs(p32).max())
        np.testing.assert_allclose(
            np.asarray(p16), np.asarray(p32), atol=2e-2 * scale
        )
        # Conservation is structural: p_grid + p_p2p == b1 in BOTH dtypes.
        np.testing.assert_allclose(
            np.asarray(g16 + p16), np.asarray(b1), rtol=1e-5, atol=1e-3
        )
