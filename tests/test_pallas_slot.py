"""Fused slot megakernel parity (ops/pallas_slot.py, interpret mode on CPU).

The acceptance contract of the raw-speed pass: ``slot_step_fused`` must be
SAME-SEED BIT-EXACT vs the existing op chain for tabular AND dqn on the
interpret-mode CPU path — slot-level (one ``slot_dynamics_batched`` call)
and episode-level (the shared-scenario trainer end to end), across the
factored, matrix and no-trading market variants, with and without the
battery. Shapes are kept tiny: interpreter-mode Pallas pays per-call
overhead, and the equivalence is shape-independent (all reductions are
per-scenario).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    BatteryConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.envs.community import (
    AgentRatings,
    init_physical,
    resolve_use_fused,
    run_episode,
    slot_dynamics_batched,
)
from p2pmicrogrid_tpu.parallel import (
    init_shared_state,
    make_scenario_traces,
    stack_scenario_arrays,
)
from p2pmicrogrid_tpu.parallel.scenarios import make_shared_episode_fn
from p2pmicrogrid_tpu.train import init_policy_state, make_policy

S, A, T = 4, 6, 8


def _cfg(impl="tabular", **sim_kw):
    sim = dict(n_agents=A, n_scenarios=S)
    sim.update(sim_kw)
    return default_config(
        sim=SimConfig(**sim), train=TrainConfig(implementation=impl)
    )


def _setup(cfg, seed=0):
    ratings = make_ratings(cfg, np.random.default_rng(42))
    traces = make_scenario_traces(cfg, seed=seed)
    arrays = stack_scenario_arrays(cfg, traces, ratings)
    ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))
    policy = make_policy(cfg)
    ps, scen = init_shared_state(cfg, jax.random.PRNGKey(seed))
    return ratings, ratings_j, arrays, policy, ps, scen


def _slot_xs(arrays, t=0):
    return (
        arrays.time[:, t],
        arrays.t_out[:, t],
        arrays.load_w[:, t],
        arrays.pv_w[:, t],
        arrays.next_time[:, t],
        arrays.next_load_w[:, t],
        arrays.next_pv_w[:, t],
    )


def _rand_state(cfg, ps, seed=7):
    """Perturb the learner state so argmaxes/ties are non-trivial (a
    zero-init Q-table argmaxes to action 0 everywhere — too easy)."""
    rng = np.random.default_rng(seed)
    if cfg.train.implementation == "tabular":
        q = rng.standard_normal(ps.q_table.shape).astype(np.float32) * 0.1
        return ps._replace(q_table=jnp.asarray(q))
    return ps


def _assert_tree_equal(a, b, what):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=f"{what}: leaf {i}"
        )


def _slot_both(cfg, explore=True, seed=3, state_seed=7):
    """One jitted slot through both paths. Jitted deliberately: the training
    drivers always jit the slot, and the UNJITTED chain itself drifts ~1 ulp
    from its own jitted form (XLA fusion differences) — the contract is
    program-vs-program, not eager-vs-program."""
    ratings, ratings_j, arrays, policy, ps, _ = _setup(cfg)
    ps = _rand_state(cfg, ps, seed=state_seed)
    phys = jax.vmap(lambda k: init_physical(cfg, k))(
        jax.random.split(jax.random.PRNGKey(1), S)
    )
    xs = _slot_xs(arrays)
    key = jax.random.PRNGKey(seed)

    @jax.jit
    def ref_fn(ps, phys, xs, key):
        return slot_dynamics_batched(
            cfg, policy, ps, phys, xs, key, ratings_j, explore=explore
        )

    @jax.jit
    def fused_fn(ps, phys, xs, key):
        return slot_dynamics_batched(
            cfg, policy, ps, phys, xs, key, ratings_j, explore=explore,
            fused=True,
        )

    return ref_fn(ps, phys, xs, key), fused_fn(ps, phys, xs, key)


MARKET_VARIANTS = [
    pytest.param({"market_impl": "factored"}, id="factored-r1"),
    pytest.param({"market_impl": "factored", "rounds": 0}, id="factored-r0"),
    pytest.param({"market_impl": "matrix"}, id="matrix-r1"),
    pytest.param({"market_impl": "matrix", "rounds": 2}, id="matrix-r2"),
    pytest.param({"trading": False}, id="no-trading"),
]


@pytest.mark.parametrize("impl", ["tabular", "dqn"])
@pytest.mark.parametrize("sim_kw", MARKET_VARIANTS)
def test_slot_fused_bit_exact(impl, sim_kw):
    cfg = _cfg(impl, **sim_kw)
    (phys_r, _, out_r, tr_r, _), (phys_f, _, out_f, tr_f, _) = _slot_both(cfg)
    _assert_tree_equal(phys_r, phys_f, "phys")
    _assert_tree_equal(out_r, out_f, "outputs")
    _assert_tree_equal(tr_r, tr_f, "transition")


@pytest.mark.parametrize("impl", ["tabular", "dqn"])
def test_slot_fused_greedy_bit_exact(impl):
    cfg = _cfg(impl, market_impl="factored")
    (phys_r, _, out_r, tr_r, _), (phys_f, _, out_f, tr_f, _) = _slot_both(
        cfg, explore=False
    )
    _assert_tree_equal(phys_r, phys_f, "phys")
    _assert_tree_equal(out_r, out_f, "outputs")
    _assert_tree_equal(tr_r, tr_f, "transition")


def test_slot_fused_battery_bit_exact():
    cfg = default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S, market_impl="factored"),
        train=TrainConfig(implementation="tabular"),
        battery=BatteryConfig(enabled=True),
    )
    ref, got = _slot_both(cfg)
    _assert_tree_equal(ref[0], got[0], "phys")
    _assert_tree_equal(ref[2], got[2], "outputs")
    _assert_tree_equal(ref[3], got[3], "transition")


@pytest.mark.parametrize("impl", ["tabular", "dqn"])
@pytest.mark.parametrize(
    "sim_kw",
    [
        pytest.param({"market_impl": "factored"}, id="factored"),
        pytest.param({"market_impl": "matrix"}, id="matrix"),
    ],
)
def test_episode_fused_bit_exact(impl, sim_kw):
    """Full shared-scenario training episodes (acts + learning) fused vs
    unfused: bit-identical final learner state, rewards and losses."""
    cfg = _cfg(impl, **sim_kw)
    ratings, _, arrays, policy, ps0, scen0 = _setup(cfg)
    ps0 = _rand_state(cfg, ps0)
    # Slice the day down to T slots: interpret-mode kernels pay per-call
    # overhead and the equivalence is slot-count-independent.
    arrays = jax.tree_util.tree_map(lambda x: x[:, :T], arrays)

    finals = {}
    for fused in (False, True):
        fn = make_shared_episode_fn(
            cfg, policy, arrays, ratings, fused=fused
        )
        carry = (ps0, scen0)
        ys = None
        for e in range(2):
            carry, ys = fn(carry, jax.random.PRNGKey(100 + e))
        finals[fused] = (carry, ys)
    _assert_tree_equal(finals[False][0], finals[True][0], "final state")
    _assert_tree_equal(finals[False][1], finals[True][1], "rewards/losses")


def test_run_episode_fused_bit_exact():
    """Single-scenario path: run_episode(fused=True) == the unfused chain
    (the single-scenario key structure differs from the batched one — the
    kernel must replicate it, not the batched split)."""
    from p2pmicrogrid_tpu.data import synthetic_traces
    from p2pmicrogrid_tpu.envs import build_episode_arrays

    cfg = default_config(
        sim=SimConfig(n_agents=A),
        train=TrainConfig(implementation="tabular"),
    )
    ratings = make_ratings(cfg, np.random.default_rng(42))
    traces = synthetic_traces(n_days=1, start_day=11).normalized()
    arrays = build_episode_arrays(cfg, traces, ratings)
    arrays = jax.tree_util.tree_map(lambda x: x[:T], arrays)
    policy = make_policy(cfg)
    ps = _rand_state(cfg, init_policy_state(cfg, jax.random.PRNGKey(0)))
    phys = init_physical(cfg, jax.random.PRNGKey(2))
    key = jax.random.PRNGKey(5)

    ref = run_episode(cfg, policy, ps, phys, arrays, ratings, key, fused=False)
    got = run_episode(cfg, policy, ps, phys, arrays, ratings, key, fused=True)
    _assert_tree_equal(ref[0], got[0], "phys")
    _assert_tree_equal(ref[1], got[1], "pol_state")
    _assert_tree_equal(ref[2], got[2], "outputs")


def test_fused_rejects_ddpg():
    cfg = _cfg("ddpg")
    with pytest.raises(ValueError, match="tabular/dqn"):
        make_shared_episode_fn(
            cfg, make_policy(cfg), None, make_ratings(cfg, np.random.default_rng(0)),
            arrays_fn=lambda k: None, n_scenarios=S, fused=True,
        )
    cfg2 = dataclasses.replace(cfg, sim=dataclasses.replace(cfg.sim, fused_slot=True))
    with pytest.raises(ValueError, match="tabular/dqn"):
        resolve_use_fused(cfg2)


def test_resolve_use_fused_default_off():
    assert resolve_use_fused(_cfg("tabular")) is False
    cfg = _cfg("tabular")
    cfg = dataclasses.replace(cfg, sim=dataclasses.replace(cfg.sim, fused_slot=True))
    assert resolve_use_fused(cfg) is True
