"""Native (C++) trace-generator tests.

Skip cleanly when no compiler is available; the NumPy generator remains the
functional fallback either way.
"""

import numpy as np
import pytest

from p2pmicrogrid_tpu import native
from p2pmicrogrid_tpu.config import SimConfig, default_config
from p2pmicrogrid_tpu.data.traces import (
    TESTING_DAYS,
    TRAINING_DAYS,
    VALIDATION_DAYS,
    synthetic_traces_native,
    train_validation_test_split,
)
from p2pmicrogrid_tpu.parallel import make_scenario_traces

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native tracegen unavailable: {native.build_error()}"
)


class TestNativeGenerator:
    def test_shapes_and_ranges(self):
        tr = synthetic_traces_native(n_days=3, n_profiles=4, seed=7, start_day=11)
        assert tr.time.shape == (288,)
        assert tr.load.shape == (288, 4)
        assert tr.pv.shape == (288, 4)
        # Same families as the NumPy generator: positive load, clipped PV,
        # plausible October temperatures.
        assert tr.load.min() >= 0.02 - 1e-6
        assert tr.pv.min() >= 0.0
        assert -10 < tr.t_out.mean() < 25
        # Night slots have zero PV.
        assert float(tr.pv[:8].max()) == 0.0

    def test_time_and_day_encoding(self):
        tr = synthetic_traces_native(n_days=2, start_day=11)
        np.testing.assert_allclose(tr.time[:96], np.arange(96) / 96, rtol=1e-6)
        assert set(np.unique(tr.day)) == {11, 12}

    def test_deterministic_and_seed_sensitive(self):
        a = synthetic_traces_native(seed=3)
        b = synthetic_traces_native(seed=3)
        c = synthetic_traces_native(seed=4)
        np.testing.assert_array_equal(a.load, b.load)
        assert not np.allclose(a.load, c.load)

    def test_day_splits_apply(self):
        tr = synthetic_traces_native(n_days=13, start_day=8)
        train, val, test = train_validation_test_split(tr)
        assert set(np.unique(train.day)) == set(TRAINING_DAYS)
        assert set(np.unique(val.day)) == set(VALIDATION_DAYS)
        assert set(np.unique(test.day)) == set(TESTING_DAYS)


class TestScenarioBackend:
    def test_native_scenarios_normalized_and_aligned(self):
        cfg = default_config(sim=SimConfig(n_scenarios=64))
        tr = make_scenario_traces(cfg, backend="native")
        assert tr.time.shape == (64, 96)
        # Per-scenario normalization to max 1.
        np.testing.assert_allclose(tr.load.max(axis=1), 1.0, rtol=1e-6)
        np.testing.assert_allclose(tr.pv.max(axis=1), 1.0, rtol=1e-6)
        # Shared slot grid (required by the shared-tabular update).
        assert (np.asarray(tr.time) == np.asarray(tr.time[:1])).all()
        # Scenarios are independent draws.
        assert not np.allclose(tr.load[0], tr.load[1])

    def test_auto_backend_small_s_uses_numpy_and_warns(self):
        cfg = default_config(sim=SimConfig(n_scenarios=2))
        with pytest.warns(UserWarning, match="chose 'numpy'"):
            tr = make_scenario_traces(cfg, backend="auto")
        assert tr.time.shape == (2, 96)

    def test_default_backend_is_deterministic_numpy(self):
        # The default must not depend on S or on g++ availability
        # (ADVICE round 1): same seed -> same traces at any scenario count.
        cfg = default_config(sim=SimConfig(n_scenarios=65))
        a = make_scenario_traces(cfg, n_scenarios=2, seed=7)
        b = make_scenario_traces(cfg, n_scenarios=65, seed=7)
        np.testing.assert_array_equal(np.asarray(a.load), np.asarray(b.load[:2]))
