"""Training-health surface (train/health.py): the greedy held-out eval,
the basin/slide classifier calibrated on the committed round-4 seed curves,
and the block-wise chunked trainer with warning/mitigation."""

import json
import os

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.train import init_policy_state, make_policy
from p2pmicrogrid_tpu.train.health import (
    HealthMonitor,
    classify_health,
    make_greedy_eval,
    train_chunked_with_health,
)

ARTIFACTS = os.path.join(os.path.dirname(__file__), "..", "artifacts")


def _cfg(impl="ddpg", S=2, A=3, **kw):
    return default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S),
        train=TrainConfig(implementation=impl),
        ddpg=DDPGConfig(buffer_size=32, batch_size=2, share_across_agents=True),
        **kw,
    )


class TestClassifier:
    """Thresholds against the module docstring's calibration table."""

    SLOTS = 96

    def test_calibration_table(self):
        initial = 3100.0
        # (cost, reward) -> expected status, from the committed curves.
        cases = [
            ((1200.0, -1.2), "healthy"),      # seed 0, trained
            ((3057.0, -1335.9), "healthy"),   # untrained ep 0: cost HIGH
            ((4806.0, -2629.9), "healthy"),   # seed 1 ep 20: cost HIGH
            ((608.5, -154.3), "slide"),       # seed 3 ep 60
            ((-471.2, -1375.6), "basin"),     # seed 2 ep 40
            ((-708.9, -1733.1), "basin"),     # seed 2 deep basin
        ]
        for (cost, reward), want in cases:
            got = classify_health(cost, reward, self.SLOTS, initial)
            assert got == want, f"cost={cost} reward={reward}: {got} != {want}"

    @pytest.mark.parametrize(
        "artifact,expect_entry_by,expect_basin",
        [
            ("LEARNING_northstar_r04b.json", None, False),          # seed 0
            ("LEARNING_northstar_r04b_seed1.json", None, False),    # seed 1
            ("LEARNING_northstar_r04b_seed2_full.json", 40, True),  # seed 2
            ("LEARNING_northstar_r04b_seed3_full.json", None, False),  # seed 3
        ],
    )
    def test_committed_seed_curves(self, artifact, expect_entry_by, expect_basin):
        """Replaying the committed round-4 curves through the monitor: the
        alert fires at the FIRST in-basin eval (seed 2 enters between
        episodes 20 and 40 and is flagged at 40 — within one 10-episode
        eval period of entry) and never fires for the healthy seeds."""
        path = os.path.join(ARTIFACTS, artifact)
        if not os.path.exists(path):
            pytest.skip(f"artifact {artifact} not present")
        curve = json.load(open(path))["curve"]
        mon = HealthMonitor(self.SLOTS, warn_stream=open(os.devnull, "w"))
        for row in curve:
            mon.update(row["episode"], row["greedy_cost_eur"], row["greedy_reward"])
        if expect_basin:
            assert mon.basin_entries, f"{artifact}: basin never flagged"
            assert mon.basin_entries[0] <= expect_entry_by
            assert mon.basin_exits, f"{artifact}: recovery never flagged"
        else:
            assert not mon.basin_entries, (
                f"{artifact}: false basin alert at {mon.basin_entries}"
            )

    def test_monitor_entry_exit_bookkeeping(self):
        mon = HealthMonitor(96, warn_stream=open(os.devnull, "w"))
        assert mon.update(0, 3000.0, -1300.0) == "healthy"   # untrained
        assert mon.update(10, 1500.0, -2.0) == "healthy"
        assert mon.update(20, -400.0, -1400.0) == "basin"
        assert mon.in_basin
        assert mon.update(30, -700.0, -1700.0) == "basin"
        assert mon.basin_entries == [20]                     # one entry
        assert mon.update(40, 1400.0, -1.5) == "healthy"
        assert not mon.in_basin
        assert mon.basin_exits == [40]


@pytest.mark.slow
class TestGreedyEval:
    def test_finite_and_deterministic(self):
        cfg = _cfg()
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state

        ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        ev = make_greedy_eval(cfg, policy, ratings, s_eval=2)
        c1, r1 = ev(ps, jax.random.PRNGKey(1))
        c2, r2 = ev(ps, jax.random.PRNGKey(1))
        assert np.isfinite(float(c1)) and np.isfinite(float(r1))
        # Greedy + fixed held-out arrays + same key => identical.
        assert float(c1) == float(c2) and float(r1) == float(r2)

    def test_tabular_impl_supported(self):
        cfg = _cfg(impl="tabular")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        ev = make_greedy_eval(cfg, policy, ratings, s_eval=2)
        c, r = ev(ps, jax.random.PRNGKey(1))
        assert np.isfinite(float(c)) and np.isfinite(float(r))


class _ForcedMonitor(HealthMonitor):
    """Forces basin classification for episodes in [enter, exit) — drives
    the mitigation branch deterministically in a tiny test run."""

    def __init__(self, slots, enter, exit_):
        super().__init__(slots, warn_stream=open(os.devnull, "w"))
        self._enter, self._exit = enter, exit_

    def update(self, episode, cost, reward):
        if self._enter <= episode < self._exit:
            # Values inside the basin signature.
            return super().update(episode, -500.0, -1600.0)
        return super().update(episode, 1500.0, -2.0)


@pytest.mark.slow
class TestChunkedWithHealth:
    def test_runs_and_monitors(self):
        cfg = _cfg()
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state

        ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        points = []
        ps, rewards, losses, secs, mon = train_chunked_with_health(
            cfg, policy, ps, ratings, jax.random.PRNGKey(7),
            n_episodes=4, n_chunks=2, eval_every=2, s_eval=2,
            health_cb=points.append,
            monitor=HealthMonitor(96, warn_stream=open(os.devnull, "w")),
        )
        assert rewards.shape == (4, 4)           # [episodes, K*S]
        assert [p.episode for p in points] == [0, 2, 4]
        assert all(np.isfinite(p.greedy_cost_eur) for p in points)

    def test_lr_boost_mitigation_switches_programs(self):
        """While the monitor reports basin, the boosted runner trains; the
        normal runner resumes after recovery. The state structure is shared
        so parameters flow through both programs unchanged in shape."""
        cfg = _cfg()
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state

        ps0 = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        mon = _ForcedMonitor(96, enter=2, exit_=4)
        ps, rewards, _, _, mon = train_chunked_with_health(
            cfg, policy, ps0, ratings, jax.random.PRNGKey(7),
            n_episodes=6, n_chunks=2, eval_every=2, s_eval=2,
            mitigate="lr-boost", lr_boost=3.0, monitor=mon,
        )
        assert mon.basin_entries == [2]
        assert mon.basin_exits == [4]
        assert rewards.shape == (6, 4)
        # Params actually changed (training happened through both programs).
        leaves0 = jax.tree_util.tree_leaves(ps0)
        leaves1 = jax.tree_util.tree_leaves(ps)
        assert any(
            not np.allclose(np.asarray(a), np.asarray(b))
            for a, b in zip(leaves0, leaves1)
        )

    def test_rejects_unknown_mitigation(self):
        cfg = _cfg()
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state

        ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="mitigate"):
            train_chunked_with_health(
                cfg, policy, ps, ratings, jax.random.PRNGKey(7),
                n_episodes=2, n_chunks=2, mitigate="autofix",
            )


@pytest.mark.slow
class TestCLIWiring:
    def test_train_chunked_health_logs_to_store(self, tmp_path):
        """`train --scenarios --shared --chunks` with the default health
        surface writes greedy cost+reward+status rows to training_health."""
        import sqlite3

        from p2pmicrogrid_tpu.cli import main

        db = str(tmp_path / "results.sqlite")
        rc = main([
            "train", "--agents", "2", "--scenarios", "2", "--shared",
            "--chunks", "2", "--implementation", "ddpg",
            "--episodes", "2", "--health-every", "1",
            "--model-dir", str(tmp_path / "models"),
            "--results-db", db,
        ])
        assert rc == 0
        rows = sqlite3.connect(db).execute(
            "SELECT episode, greedy_cost, greedy_reward, status "
            "FROM training_health ORDER BY episode"
        ).fetchall()
        assert [r[0] for r in rows] == [0, 1, 2]
        assert all(np.isfinite(r[1]) and np.isfinite(r[2]) for r in rows)
        assert all(r[3] in ("healthy", "slide", "basin") for r in rows)

    def test_chunk_parallel_without_chunks_errors(self, capsys):
        from p2pmicrogrid_tpu.cli import main

        with pytest.raises(SystemExit, match="chunk-parallel"):
            main([
                "train", "--agents", "2", "--scenarios", "2", "--shared",
                "--chunk-parallel", "2", "--episodes", "1",
            ])

    def test_auto_mitigation_resolution(self, tmp_path):
        """--basin-mitigate auto resolves to lr-boost for chunked ddpg
        (valid, runs) and warn for dqn/non-chunked (no usage error)."""
        from p2pmicrogrid_tpu.cli import main

        # dqn + chunks + auto must NOT error (resolves to warn).
        rc = main([
            "train", "--agents", "2", "--scenarios", "2", "--shared",
            "--chunks", "2", "--implementation", "dqn",
            "--episodes", "1", "--health-every", "1",
            "--model-dir", str(tmp_path / "m1"),
        ])
        assert rc == 0
        # explicit lr-boost for dqn still errors.
        with pytest.raises(SystemExit, match="implementation ddpg"):
            main([
                "train", "--agents", "2", "--scenarios", "2", "--shared",
                "--chunks", "2", "--implementation", "dqn",
                "--basin-mitigate", "lr-boost", "--episodes", "1",
            ])
