"""Async episode pipeline (PR 4): donated carries, device-side key
schedules, depth-2 lagged readback, and the host-sync static check.

The contract under test: the async driver produces BIT-IDENTICAL final
policy state to the synchronous escape hatch for fixed seeds (dispatch
order never changes — only readback timing moves), lagged callbacks see
exactly the sync driver's values one episode late, and donation never
invalidates a caller's passed-in state (the drivers copy-on-entry).
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    DDPGConfig,
    DQNConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.parallel import (
    init_shared_state,
    make_scenario_traces,
    stack_scenario_arrays,
    train_scenarios_chunked,
)
from p2pmicrogrid_tpu.parallel.scenarios import (
    _episode_key_schedule,
    chunk_key_schedule,
    make_shared_episode_fn,
    train_scenarios_shared,
)
from p2pmicrogrid_tpu.telemetry import AsyncDrain, MemorySink, Telemetry
from p2pmicrogrid_tpu.train import make_policy

REPO = os.path.join(os.path.dirname(__file__), "..")


def _cfg(impl="tabular", S=2, A=2, **kw):
    return default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S),
        train=TrainConfig(implementation=impl),
        dqn=DQNConfig(buffer_size=16, batch_size=4),
        ddpg=DDPGConfig(buffer_size=32, batch_size=2, share_across_agents=True),
        **kw,
    )


def _leaves_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


class TestKeySchedules:
    def test_chunk_schedule_matches_fold_in_stack(self):
        """The jitted [E, K] schedule is bit-identical to the host loop of
        fold_in(fold_in(key, e), c) stacks it replaces."""
        key = jax.random.PRNGKey(3)
        sched = np.asarray(chunk_key_schedule(key, 5, 4, 3))
        assert sched.shape[:2] == (4, 3)
        for e in range(4):
            for c in range(3):
                ref = jax.random.fold_in(jax.random.fold_in(key, 5 + e), c)
                assert np.array_equal(sched[e, c], np.asarray(ref))

    def test_episode_schedule_matches_split_chain(self):
        """One jitted scan reproduces the sequential `key, k = split(key)`
        chain of the old host loop, bit-for-bit."""
        key = jax.random.PRNGKey(9)
        refs, k = [], key
        for _ in range(5):
            k, sub = jax.random.split(k)
            refs.append(np.asarray(sub))
        assert np.array_equal(
            np.asarray(_episode_key_schedule(key, 5)), np.stack(refs)
        )


class TestAsyncDrain:
    def test_depth2_lags_consumption_by_one_dispatch(self):
        """The drain-order contract: episode e's consume runs AFTER episode
        e+1 was dispatched, in FIFO order, with a full flush at the end."""
        events = []
        drain = AsyncDrain(depth=2)
        for e in range(3):
            events.append(("dispatch", e))
            drain.push(e, (np.float32(e),), lambda tag, host: events.append(("drain", tag)))
        drain.flush()
        assert events == [
            ("dispatch", 0), ("dispatch", 1), ("drain", 0),
            ("dispatch", 2), ("drain", 1), ("drain", 2),
        ]

    def test_depth1_is_synchronous(self):
        events = []
        drain = AsyncDrain(depth=1)
        for e in range(2):
            events.append(("dispatch", e))
            drain.push(e, (np.float32(e),), lambda tag, host: events.append(("drain", tag)))
        assert events == [
            ("dispatch", 0), ("drain", 0), ("dispatch", 1), ("drain", 1),
        ]

    def test_resolves_device_arrays_and_records_metrics(self):
        tel = Telemetry(run_id="t", sinks=[MemorySink()])
        drain = AsyncDrain(depth=2, telemetry=tel)
        got = {}
        for e in range(3):
            drain.push(
                e,
                (jnp.full((2,), e, jnp.float32), None),
                lambda tag, host: got.update({tag: host}),
            )
        assert drain.finish() >= 0.0
        assert sorted(got) == [0, 1, 2]
        r, none = got[1]
        assert isinstance(r, np.ndarray) and np.array_equal(r, [1.0, 1.0])
        assert none is None
        s = tel.summary()
        assert "train.host_blocked_fraction" in s["gauges"]
        assert s["gauges"]["train.pipeline_depth"] == 2.0
        # 3 dispatches -> 2 gap samples; a span pair per drained episode.
        assert s["histograms"]["train.dispatch_gap_ms"]["count"] == 2
        assert s["spans"]["pipeline_drain"]["count"] == 3

    def test_rejects_zero_depth(self):
        with pytest.raises(ValueError, match="depth"):
            AsyncDrain(depth=0)


class TestBitExactness:
    """Acceptance: async driver == sync driver, bit for bit, fixed seeds."""

    def test_chunked_tabular_sync_vs_async(self):
        cfg = _cfg("tabular")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        sync, r_s, l_s, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=3, n_chunks=2, pipeline=False,
        )
        anc, r_a, l_a, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=3, n_chunks=2, pipeline=True,
        )
        assert _leaves_equal(sync, anc)
        np.testing.assert_array_equal(r_s, r_a)
        np.testing.assert_array_equal(l_s, l_a)
        # Donation safety: the caller's state survived the donating driver
        # (defensive copy-on-entry) — readable, and still the init values.
        _ = np.asarray(jax.tree_util.tree_leaves(ps)[0])

    def test_shared_dqn_sync_vs_async(self):
        cfg = _cfg("dqn")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        traces = make_scenario_traces(cfg, seed=0)
        arrays = stack_scenario_arrays(cfg, traces, ratings)
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
        out_s = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(2), 3,
            replay_s=scen, pipeline=False,
        )
        out_a = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(2), 3,
            replay_s=scen, pipeline=True,
        )
        # Policy params, per-scenario replay, and reward/loss records all
        # match bit-for-bit (donation + lagged readback change nothing).
        assert _leaves_equal(out_s[:2], out_a[:2])
        np.testing.assert_array_equal(out_s[2], out_a[2])
        np.testing.assert_array_equal(out_s[3], out_a[3])
        _ = np.asarray(jax.tree_util.tree_leaves(ps)[0])


class TestDonationSafety:
    def test_escape_hatch_episode_fn_does_not_donate(self):
        """pipeline=False builds a non-donating program: the same carry can
        drive it twice (no use-after-donate on the escape-hatch path)."""
        cfg = _cfg("tabular")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        arrays = stack_scenario_arrays(
            cfg, make_scenario_traces(cfg, seed=0), ratings
        )
        fn = make_shared_episode_fn(cfg, policy, arrays, ratings, donate=False)
        carry = init_shared_state(cfg, jax.random.PRNGKey(0))
        a1, _ = fn(carry, jax.random.PRNGKey(1))
        a2, _ = fn(carry, jax.random.PRNGKey(1))  # carry still alive
        assert _leaves_equal(a1, a2)

    def test_donating_episode_fn_consumes_its_carry(self):
        """donate=True consumes the carry in place: reusing it is a loud
        use-after-donate error, not silent corruption."""
        cfg = _cfg("tabular")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        arrays = stack_scenario_arrays(
            cfg, make_scenario_traces(cfg, seed=0), ratings
        )
        fn = make_shared_episode_fn(cfg, policy, arrays, ratings, donate=True)
        carry = init_shared_state(cfg, jax.random.PRNGKey(0))
        carry2, _ = fn(carry, jax.random.PRNGKey(1))
        with pytest.raises(RuntimeError, match="deleted"):
            np.asarray(jax.tree_util.tree_leaves(carry)[0]) + 0
        # The returned carry is the live one.
        _ = np.asarray(jax.tree_util.tree_leaves(carry2)[0])


class TestLaggedCallbacks:
    def test_episode_cb_values_match_sync_in_order(self):
        cfg = _cfg("tabular")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))

        def record_into(log):
            return lambda ep, r, l, carry: log.append((ep, r.copy(), l.copy()))

        log_s, log_a = [], []
        train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=3, n_chunks=2, pipeline=False,
            episode_cb=record_into(log_s),
        )
        train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=3, n_chunks=2, pipeline=True,
            episode_cb=record_into(log_a),
        )
        assert [e for e, _, _ in log_a] == [0, 1, 2]
        for (es, rs, ls), (ea, ra, la) in zip(log_s, log_a):
            assert es == ea
            np.testing.assert_array_equal(rs, ra)
            np.testing.assert_array_equal(ls, la)

    def test_lagged_carry_is_donated_unless_carry_sync(self):
        """The drain-order contract made observable: under donation, the
        carry a LAGGED callback sees was consumed by the next episode's
        dispatch — except at the final flush, and except at episodes a
        carry_sync predicate forces a synchronous drain for."""
        cfg = _cfg("tabular")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))

        def probe(log):
            def cb(ep, r, l, carry):
                try:
                    np.asarray(jax.tree_util.tree_leaves(carry)[0]) + 0
                    log.append((ep, True))
                except RuntimeError:
                    log.append((ep, False))
            return cb

        alive = []
        train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=3, n_chunks=2, pipeline=True, episode_cb=probe(alive),
        )
        # Episodes 0 and 1 drained one dispatch late (carry donated);
        # episode 2 drained at the final flush (carry alive).
        assert alive == [(0, False), (1, False), (2, True)]

        synced = []
        train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=3, n_chunks=2, pipeline=True, episode_cb=probe(synced),
            carry_sync=lambda ep: True,
        )
        assert synced == [(0, True), (1, True), (2, True)]


class TestTrainCommunityPipeline:
    def test_bit_exact_and_checkpoints_episode_exact(self):
        from p2pmicrogrid_tpu.data import synthetic_traces
        from p2pmicrogrid_tpu.train import (
            init_policy_state,
            train_community,
        )

        cfg = default_config(
            sim=SimConfig(n_agents=2),
            train=TrainConfig(
                implementation="tabular", max_episodes=4,
                episodes_per_jit_block=2, save_episodes=2,
            ),
        )
        traces = synthetic_traces(n_days=1, start_day=11).normalized()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))

        def saver(log):
            return lambda ep, s: log.append(
                (ep, np.asarray(jax.tree_util.tree_leaves(s)[0]).copy())
            )

        ck_s, ck_a = [], []
        res_s = train_community(
            cfg, policy, ps, traces, ratings, jax.random.PRNGKey(0),
            pipeline=False, checkpoint_cb=saver(ck_s),
        )
        tel = Telemetry(run_id="t", sinks=[MemorySink()])
        res_a = train_community(
            cfg, policy, ps, traces, ratings, jax.random.PRNGKey(0),
            pipeline=True, checkpoint_cb=saver(ck_a), telemetry=tel,
        )
        assert _leaves_equal(res_s.pol_state, res_a.pol_state)
        assert res_s.episode_rewards == res_a.episode_rewards
        # Checkpoints fire at the same episodes with the same (live,
        # episode-exact) state: the pipeline drains synchronously before
        # the next dispatch can donate a to-be-checkpointed carry.
        assert [e for e, _ in ck_a] == [e for e, _ in ck_s] == [1, 3]
        for (_, a), (_, b) in zip(ck_s, ck_a):
            np.testing.assert_array_equal(a, b)
        s = tel.summary()
        assert "train.host_blocked_fraction" in s["gauges"]
        assert "pipeline_drain" in s["spans"]
        assert "train_block" in s["spans"]


class TestChunkedTelemetry:
    def test_pipeline_gauges_spans_and_lagged_device_counters(self):
        """The default chunked driver with telemetry keeps its device-counter
        events (now consumed lagged) and gains the pipeline observability."""
        cfg = _cfg("tabular")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state

        ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        sink = MemorySink()
        tel = Telemetry(run_id="t", sinks=[sink])
        train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=2, n_chunks=2, telemetry=tel, pipeline=True,
        )
        s = tel.summary()
        assert "train.host_blocked_fraction" in s["gauges"]
        assert "replay.fill_fraction" in s["gauges"]
        assert "train.dispatch_gap_ms" in s["histograms"]
        assert s["spans"]["pipeline_dispatch"]["count"] == 2
        assert s["spans"]["pipeline_drain"]["count"] == 2
        dc_events = [
            r for r in sink.records if r.get("kind") == "device_counters"
        ]
        assert [r["episode"] for r in dc_events] == [0, 1]


@pytest.fixture(scope="module")
def host_sync_checker():
    spec = importlib.util.spec_from_file_location(
        "check_host_sync", os.path.join(REPO, "tools", "check_host_sync.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestCheckHostSync:
    def test_repo_hot_paths_are_clean(self, host_sync_checker):
        """Acceptance: the checker runs clean on the shipped code."""
        assert host_sync_checker.check_host_sync(os.path.abspath(REPO)) == []

    def test_flags_unannotated_readback(self, host_sync_checker, tmp_path):
        rel = host_sync_checker.HOT_PATH_FILES[0]
        path = tmp_path / rel
        path.parent.mkdir(parents=True)
        path.write_text(
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.asarray(x)\n"
        )
        problems = host_sync_checker.check_host_sync(str(tmp_path))
        assert len(problems) == 1 and "np.asarray" in problems[0]

    def test_annotated_and_string_mentions_pass(self, host_sync_checker, tmp_path):
        rel = host_sync_checker.HOT_PATH_FILES[0]
        path = tmp_path / rel
        path.parent.mkdir(parents=True)
        path.write_text(
            '"""Docs may discuss np.asarray( and block_until_ready( freely."""\n'
            "import numpy as np\n"
            "def f(x, y):\n"
            "    # host-sync: test fixture annotation.\n"
            "    a = np.asarray(x)\n"
            "    b = np.asarray(y)  # host-sync: inline annotation\n"
            "    return a, b\n"
        )
        assert host_sync_checker.check_host_sync(str(tmp_path)) == []

    def test_wired_into_check_all(self, host_sync_checker, tmp_path):
        """check_artifacts_schema.check_all sweeps host-sync problems too."""
        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(REPO, "tools", "check_artifacts_schema.py"),
        )
        schema = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(schema)
        rel = host_sync_checker.HOT_PATH_FILES[0]
        path = tmp_path / rel
        path.parent.mkdir(parents=True)
        path.write_text("import numpy as np\nx = np.asarray([1])\n")
        problems = schema.check_all(str(tmp_path))
        assert any("un-annotated blocking readback" in p for p in problems)


class TestWatchMode:
    def test_cli_watch_streams_joined_rows_once(self, tmp_path, capsys):
        from p2pmicrogrid_tpu.cli import main
        from p2pmicrogrid_tpu.data.results import ResultsStore
        from p2pmicrogrid_tpu.telemetry import SqliteSink

        db = str(tmp_path / "r.db")
        tel = Telemetry(
            run_id="run-W", sinks=[SqliteSink(db)],
            manifest={"config_hash": "cfg-W", "created": "now"},
        )
        tel.gauge("train.host_blocked_fraction", 0.01)
        tel.event("progress", episode=1)
        tel.close()
        with ResultsStore(db) as store:
            store.log_eval_run(
                "2-agent", "tabular", False, config_hash="cfg-W",
                n_days=1, total_cost_eur=0.5,
            )
        rc = main([
            "telemetry-query", "--results-db", db,
            "--watch", "--max-polls", "2", "--interval", "0",
        ])
        assert rc == 0
        lines = [
            json.loads(l) for l in capsys.readouterr().out.splitlines() if l
        ]
        # Two polls, one joined row: emitted exactly once (deduped tail).
        assert len(lines) == 1
        assert lines[0]["run_id"] == "run-W"
        assert lines[0]["config_hash"] == "cfg-W"

    def test_cli_watch_survives_pre_warehouse_db(self, tmp_path, capsys):
        import sqlite3

        from p2pmicrogrid_tpu.cli import main

        db = str(tmp_path / "plain.db")
        sqlite3.connect(db).close()  # empty DB: no warehouse tables yet
        rc = main([
            "telemetry-query", "--results-db", db,
            "--watch", "--max-polls", "1", "--interval", "0",
        ])
        assert rc == 0
        assert capsys.readouterr().out.strip() == ""

    def test_cli_watch_fails_loud_on_corrupt_db(self, tmp_path, capsys):
        """A non-database file must exit with an error, not spin silently
        (only 'no such table' reads as pre-warehouse)."""
        from p2pmicrogrid_tpu.cli import main

        db = tmp_path / "corrupt.db"
        db.write_text("this is not a sqlite database, not even close......")
        rc = main([
            "telemetry-query", "--results-db", str(db),
            "--watch", "--max-polls", "0", "--interval", "0",
        ])
        assert rc == 1


class TestServePlacement:
    def test_pick_serve_device_on_cpu_backend(self):
        from p2pmicrogrid_tpu.train.placement import pick_serve_device

        dev, reason = pick_serve_device("tabular", 2)
        assert dev is None and "host XLA-CPU" in reason

    def test_engine_honours_device_pin(self, tmp_path):
        from p2pmicrogrid_tpu.serve import PolicyEngine, export_policy_bundle
        from p2pmicrogrid_tpu.train import init_policy_state

        cfg = _cfg("tabular", S=1)
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "b"))
        eng = PolicyEngine(bundle_dir=bundle, max_batch=4, device="cpu")
        assert eng.device is not None and eng.device.platform == "cpu"
        out = eng.act(np.zeros((3, 2, 4), np.float32))
        assert out.shape == (3, 2)
        sessions = eng.init_sessions(2)
        sessions, hp = eng.step(sessions, np.zeros((2, 2, 4), np.float32))
        assert hp.shape == (2, 2)

    def test_engine_rejects_unknown_device(self, tmp_path):
        from p2pmicrogrid_tpu.serve import PolicyEngine, export_policy_bundle
        from p2pmicrogrid_tpu.train import init_policy_state

        cfg = _cfg("tabular", S=1)
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "b"))
        with pytest.raises(ValueError, match="device"):
            PolicyEngine(bundle_dir=bundle, max_batch=4, device="tpu9000")

    def _serve_table(self, tmp_path, rows):
        """A committed-capture-shaped CROSSOVER_SERVE file in tmp_path."""
        import json

        doc = {"kind": "serve_crossover", "rows": rows}
        (tmp_path / "CROSSOVER_SERVE_r99.json").write_text(json.dumps(doc))
        return str(tmp_path)

    def test_serve_crossover_table_decides_placement(self, tmp_path):
        """ISSUE 5 satellite: with a measured (n_agents, max_batch) serve
        table, auto-placement is batch-width aware — the B=1 training
        caveat is gone."""
        from p2pmicrogrid_tpu.train.placement import pick_serve_device

        art = self._serve_table(tmp_path, [
            {"implementation": "tabular", "n_agents": 2, "max_batch": 1,
             "tpu_over_cpu": 0.05},
            {"implementation": "tabular", "n_agents": 2, "max_batch": 64,
             "tpu_over_cpu": 4.0},
        ])
        # Narrow serving: the measured point says CPU wins -> host pin.
        dev, reason = pick_serve_device(
            "tabular", 2, max_batch=1, default_backend="tpu",
            artifacts_dir=art,
        )
        assert dev is not None and dev.platform == "cpu"
        assert "serve crossover" in reason and "max_batch=1" in reason
        # A capture point so CPU-favorable it rounds to 0.0 must not
        # divide by zero — it reports the bound.
        zero_dir = tmp_path / "zero"
        zero_dir.mkdir()
        art0 = self._serve_table(zero_dir, [
            {"implementation": "dqn", "n_agents": 2, "max_batch": 1,
             "tpu_over_cpu": 0.0},
        ])
        dev, reason = pick_serve_device(
            "dqn", 2, max_batch=1, default_backend="tpu",
            artifacts_dir=art0,
        )
        assert dev is not None and ">1000x" in reason
        # Wide bucket: the measured point says the accelerator wins.
        dev, reason = pick_serve_device(
            "tabular", 2, max_batch=64, default_backend="tpu",
            artifacts_dir=art,
        )
        assert dev is None and "tpu wins" in reason.lower()

    def test_no_serve_table_wide_batch_stays_on_default(self, tmp_path):
        """Without a serve measurement, wide-batch configs must NOT
        inherit the B=1 training table's CPU pin (a padded bucket can
        fill the accelerator); max_batch=1 still may."""
        from p2pmicrogrid_tpu.train.placement import pick_serve_device

        empty = str(tmp_path)  # no CROSSOVER_SERVE_* here
        dev, reason = pick_serve_device(
            "tabular", 2, max_batch=64, default_backend="tpu",
            artifacts_dir=empty,
        )
        assert dev is None and "no serve-specific crossover" in reason
        dev, reason = pick_serve_device(
            "tabular", 2, max_batch=1, default_backend="tpu",
            artifacts_dir=empty,
        )
        assert dev is not None and dev.platform == "cpu"
        assert "B=1" in reason

    def test_serve_table_nearest_point_lookup(self, tmp_path):
        from p2pmicrogrid_tpu.train.placement import serve_cpu_advantage

        art = self._serve_table(tmp_path, [
            {"implementation": "ddpg", "n_agents": 10, "max_batch": 8,
             "tpu_over_cpu": 0.5},
            {"implementation": "ddpg", "n_agents": 100, "max_batch": 64,
             "tpu_over_cpu": 3.0},
        ])
        ratio, source = serve_cpu_advantage("ddpg", 12, 8, art)
        assert ratio == 0.5 and "A=10" in source
        ratio, source = serve_cpu_advantage("ddpg", 80, 32, art)
        assert ratio == 3.0 and "A=100" in source
        assert serve_cpu_advantage("tabular", 2, 1, art) is None

    def test_committed_serve_crossover_capture_loads(self):
        """ISSUE 12 satellite: the committed CROSSOVER_SERVE capture gives
        the loader (live since the gateway round) a real non-empty table."""
        from p2pmicrogrid_tpu.train.placement import (
            load_serve_crossover,
            serve_cpu_advantage,
        )

        table = load_serve_crossover()
        assert table, "artifacts/CROSSOVER_SERVE_*.json should be committed"
        measured = serve_cpu_advantage("tabular", 10, 8)
        assert measured is not None
        ratio, source = measured
        assert ratio > 0 and "measured at" in source

    def test_host_only_capture_ignored_on_accelerator(self, tmp_path):
        """A capture measured WITHOUT an accelerator (accelerator: false)
        must not decide placement on an accelerator host — its ratios
        measured CPU-vs-CPU; the honest fallbacks apply instead."""
        import json

        from p2pmicrogrid_tpu.train.placement import (
            pick_serve_device,
            serve_crossover_is_host_only,
        )

        doc = {
            "kind": "serve_crossover", "accelerator": False,
            "rows": [
                {"implementation": "tabular", "n_agents": 2, "max_batch": 64,
                 "tpu_over_cpu": 1.0},
            ],
        }
        (tmp_path / "CROSSOVER_SERVE_r98.json").write_text(json.dumps(doc))
        art = str(tmp_path)
        assert serve_crossover_is_host_only(art) is True
        dev, reason = pick_serve_device(
            "tabular", 2, max_batch=64, default_backend="tpu",
            artifacts_dir=art,
        )
        assert dev is None and "no serve-specific crossover" in reason

    def test_gateway_modules_on_host_sync_hot_path(self, host_sync_checker):
        """The async gateway/registry handlers are hot-path modules: one
        blocking readback stalls every connected household."""
        rels = {os.path.basename(p) for p in host_sync_checker.HOT_PATH_FILES}
        assert {"gateway.py", "registry.py", "engine.py"} <= rels


def test_bench_registry_includes_chunked_pipeline():
    from p2pmicrogrid_tpu.benchmarks import BENCHES, CPU_RETRYABLE

    assert "chunked_pipeline" in BENCHES
    assert "chunked_pipeline" in CPU_RETRYABLE
    assert list(BENCHES)[-1] == "northstar"  # headline row stays last


class TestAsyncHealthEval:
    """The block-boundary health eval rides the shared pipeline (ISSUE 11
    satellite): at eval_every=1 the old design drained the whole pipeline
    at EVERY boundary; now the eval dispatches on the live carry and its
    readback resolves lagged — with bit-identical training state, health
    points and classifications, and the synchronous drain kept exactly
    when a guard or the lr-boost mitigation reads the eval."""

    def _run(self, pipeline, flush_counts, guard=None):
        from p2pmicrogrid_tpu.envs import make_ratings
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state
        from p2pmicrogrid_tpu.train.health import train_chunked_with_health

        cfg = _cfg(S=2, A=2)
        policy = make_policy(cfg)
        ratings = make_ratings(cfg, np.random.default_rng(0))
        ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        flush_counts.clear()
        flush_counts.append(0)
        return train_chunked_with_health(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=4, n_chunks=1, eval_every=1, telemetry=None,
            pipeline=pipeline, s_eval=2, guard=guard,
        )

    def test_eval_every_1_bit_exact_and_unthrottled(self, monkeypatch):
        counts: list = []
        orig_flush = AsyncDrain.flush

        def counting_flush(self):
            counts[0] += 1
            return orig_flush(self)

        monkeypatch.setattr(AsyncDrain, "flush", counting_flush)
        ps_a, r_a, l_a, _, mon_a = self._run(True, counts)
        n_async = counts[0]
        ps_s, r_s, l_s, _, mon_s = self._run(False, counts)
        n_sync = counts[0]
        # Pipelined evals: ONE terminal flush (+ finish), not one per
        # boundary — that per-boundary drain was the measurable cost at
        # eval_every=1.
        assert n_async <= 3
        assert n_sync >= 5  # depth-1: every boundary drains
        assert _leaves_equal(ps_a, ps_s)
        np.testing.assert_array_equal(r_a, r_s)
        np.testing.assert_array_equal(l_a, l_s)
        assert [tuple(p) for p in mon_a.points] == [
            tuple(p) for p in mon_s.points
        ]
        # Lagged consumption preserved eval ORDER (episode monotone).
        assert [p.episode for p in mon_a.points] == [0, 1, 2, 3, 4]

    def test_guard_keeps_synchronous_drain(self, monkeypatch):
        """A divergence guard must observe each eval BEFORE the next
        block: the drain stays synchronous when one is attached."""
        from p2pmicrogrid_tpu.train.resilience import DivergenceGuard

        counts: list = []
        orig_flush = AsyncDrain.flush

        def counting_flush(self):
            counts[0] += 1
            return orig_flush(self)

        monkeypatch.setattr(AsyncDrain, "flush", counting_flush)
        ps_g, r_g, l_g, _, mon_g = self._run(
            True, counts, guard=DivergenceGuard()
        )
        assert counts[0] >= 5  # per-boundary flush kept
        ps_s, r_s, l_s, _, mon_s = self._run(False, counts)
        assert _leaves_equal(ps_g, ps_s)
        assert [tuple(p) for p in mon_g.points] == [
            tuple(p) for p in mon_s.points
        ]
