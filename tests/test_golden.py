"""Golden-trace regression test (SURVEY.md section 4, oracle c).

A fully deterministic scripted 2-agent greedy episode (planted Q-table, fixed
seeds, CPU) is pinned to values generated at framework version 0.1.0. Any
semantic drift in observation assembly, negotiation, market clearing,
settlement, rewards, or the thermal model shows up here first.
"""

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.data import synthetic_traces
from p2pmicrogrid_tpu.envs import (
    build_episode_arrays,
    init_physical,
    make_ratings,
    run_episode,
)
from p2pmicrogrid_tpu.models import tabular_init
from p2pmicrogrid_tpu.train import make_policy

GOLDEN = {
    "cost": [
        [0.002175, 0.087167],
        [0.082664, 0.001432],
        [0.042346, 0.039303],
        [0.037923, 0.036898],
    ],
    "p_grid": [
        [77.043663, 3087.079102],
        [3103.469238, 53.755768],
        [1687.529175, 1566.260376],
        [1604.566772, 1561.177368],
    ],
    "t_in": [
        [21.301205, 20.728098],
        [20.790424, 21.540831],
        [21.560457, 21.001354],
        [21.548647, 21.147606],
    ],
    "hp_power_w": [
        [0.0, 3000.0],
        [3000.0, 0.0],
        [1500.0, 1500.0],
        [1500.0, 1500.0],
    ],
    "max_in": [4565.099121, 4606.924316],
}


def test_scripted_episode_matches_golden():
    cfg = default_config(
        sim=SimConfig(n_agents=2, rounds=1),
        train=TrainConfig(implementation="tabular"),
    )
    traces = synthetic_traces(n_days=1, start_day=11).normalized()
    ratings = make_ratings(cfg, np.random.default_rng(42))
    np.testing.assert_allclose(ratings.max_in, GOLDEN["max_in"], rtol=1e-5)

    arrays = build_episode_arrays(cfg, traces, ratings)
    policy = make_policy(cfg)
    ps = tabular_init(cfg.qlearning, 2)
    ps = ps._replace(
        q_table=jax.random.normal(jax.random.PRNGKey(5), ps.q_table.shape)
    )
    phys = init_physical(cfg, jax.random.PRNGKey(0))

    _, _, out = run_episode(
        cfg, policy, ps, phys, arrays, ratings, jax.random.PRNGKey(7), training=False
    )

    for name in ("cost", "p_grid", "t_in", "hp_power_w"):
        np.testing.assert_allclose(
            np.asarray(getattr(out, name))[:4],
            GOLDEN[name],
            rtol=2e-4,
            atol=1e-5,
            err_msg=name,
        )
    # Reward is exactly -cost here (temperatures stay inside the comfort band
    # in these slots, zero penalty).
    np.testing.assert_allclose(
        np.asarray(out.reward)[:4], -np.asarray(GOLDEN["cost"]), rtol=2e-4, atol=1e-5
    )


# Pinned on CPU at the round-2 state of the learning dynamics: epsilon-greedy
# action draws, per-slot Bellman updates inside the scan, reward assembly.
GOLDEN_TRAIN = {
    "reward_first4": [
        -0.044529, -0.087167, -0.082664, -0.041386,
        -10.071978, -0.076942, -0.002471, -11.820530,
    ],
    "q_delta_abs_sum": 0.0242695,
    "q_cells_changed": 175,
}


def test_training_episode_matches_golden():
    """Training-path golden (round-1 VERDICT weak #8): one tabular training
    episode with fixed keys must reproduce the pinned per-slot rewards and
    Q-table update statistics — any change to the epsilon-greedy draw order,
    TD target, learning rate application, or scatter semantics fails here."""
    cfg = default_config(
        sim=SimConfig(n_agents=2, rounds=1),
        train=TrainConfig(implementation="tabular"),
    )
    traces = synthetic_traces(n_days=1, start_day=11).normalized()
    ratings = make_ratings(cfg, np.random.default_rng(42))
    arrays = build_episode_arrays(cfg, traces, ratings)
    policy = make_policy(cfg)
    ps = tabular_init(cfg.qlearning, 2)
    ps = ps._replace(
        q_table=jax.random.normal(jax.random.PRNGKey(5), ps.q_table.shape)
    )
    phys = init_physical(cfg, jax.random.PRNGKey(0))

    _, ps2, out = run_episode(
        cfg, policy, ps, phys, arrays, ratings, jax.random.PRNGKey(7), training=True
    )

    delta = np.asarray(ps2.q_table - ps.q_table)
    np.testing.assert_allclose(
        np.asarray(out.reward)[:4].reshape(-1),
        GOLDEN_TRAIN["reward_first4"],
        rtol=2e-4,
        atol=1e-5,
    )
    np.testing.assert_allclose(
        np.abs(delta).sum(), GOLDEN_TRAIN["q_delta_abs_sum"], rtol=1e-3
    )
    assert int((delta != 0).sum()) == GOLDEN_TRAIN["q_cells_changed"]
