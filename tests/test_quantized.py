"""int8 quantized bundles + AOT bucket programs (the serving half of the
raw-speed pass).

The error-bound contract (serve/export.py):

* discrete policies (tabular, dqn) — export→load round trip serves a
  BIT-EXACT greedy argmax vs the float32 bundle, across padding buckets;
* continuous actors (ddpg) — the measured max-ulp action distance is
  recorded in the manifest and must fit the budget;
* the promotion gate refuses a quantized candidate exceeding its budget;
* export-time AOT bucket programs make a same-architecture engine's warmup
  (the gateway hot-swap path) adopt cached executables instead of
  recompiling.
"""

import json
import os

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.serve.engine import (
    PolicyEngine,
    clear_aot_program_cache,
)
from p2pmicrogrid_tpu.serve.export import (
    DEFAULT_ULP_BUDGET,
    calibration_obs,
    export_policy_bundle,
    load_policy_bundle,
)
from p2pmicrogrid_tpu.train import init_policy_state

A = 4


def _cfg(impl, **kw):
    return default_config(
        sim=SimConfig(n_agents=A),
        train=TrainConfig(implementation=impl),
        **kw,
    )


def _state(cfg, seed=0, perturb=0.1):
    ps = init_policy_state(cfg, jax.random.PRNGKey(seed))
    if cfg.train.implementation == "tabular" and perturb:
        rng = np.random.default_rng(seed + 1)
        q = rng.standard_normal(ps.q_table.shape).astype(np.float32) * perturb
        # Plant exact near-ties so the argmax-repair pass has real work:
        # entries closer than one quantization step WILL collapse or flip
        # without repair.
        q[:, 0, 0, 0, 0, 0] = 0.5
        q[:, 0, 0, 0, 0, 1] = 0.5 - 1e-6
        ps = ps._replace(q_table=q)
    if cfg.train.implementation == "dqn":
        # A decisive network: scale the action-input row of the first layer
        # so inter-action Q gaps dwarf the int8 weight noise. A fresh-init
        # net has near-tied actions at some calibration points, and the
        # export REFUSES those (the documented contract) — which
        # test_int8_export_refuses_tied_dqn asserts separately.
        k = np.asarray(ps.online["Dense_0"]["kernel"]).copy()
        k[:, -1, :] *= 20.0
        online = dict(ps.online)
        online["Dense_0"] = dict(online["Dense_0"], kernel=k)
        ps = ps._replace(online=online)
    return ps


def _export_pair(cfg, ps, tmp, **kw):
    f32_dir = export_policy_bundle(cfg, ps, os.path.join(tmp, "f32"))
    q_dir = export_policy_bundle(
        cfg, ps, os.path.join(tmp, "int8"), dtype="int8", **kw
    )
    return f32_dir, q_dir


@pytest.mark.parametrize("impl", ["tabular", "dqn"])
def test_int8_discrete_greedy_bit_exact_two_buckets(impl, tmp_path):
    """Export→load round trip: the int8 bundle's greedy actions equal the
    float32 bundle's BIT-EXACTLY, through the real engine, across two
    padding buckets."""
    cfg = _cfg(impl)
    ps = _state(cfg)
    f32_dir, q_dir = _export_pair(cfg, ps, str(tmp_path))

    eng_f32 = PolicyEngine(bundle_dir=f32_dir, max_batch=8)
    eng_q = PolicyEngine(bundle_dir=q_dir, max_batch=8)
    rng = np.random.default_rng(3)
    for batch in (3, 8):  # two padding buckets (4 and 8)
        obs = np.concatenate(
            [
                rng.uniform(0, 1, (batch, A, 1)),
                rng.uniform(-1, 1, (batch, A, 3)),
            ],
            axis=-1,
        ).astype(np.float32)
        np.testing.assert_array_equal(eng_f32.act(obs), eng_q.act(obs))


def test_int8_manifest_contract_fields(tmp_path):
    cfg = _cfg("tabular")
    _, q_dir = _export_pair(cfg, _state(cfg), str(tmp_path))
    manifest, raw = load_policy_bundle(q_dir, dequantize=False)
    assert manifest["dtype"] == "int8"
    quant = manifest["quant"]
    assert quant["scheme"] == "symmetric-per-leaf-int8"
    assert quant["scales"] and all(
        isinstance(s, float) and s > 0 for s in quant["scales"].values()
    )
    eb = quant["error_bound"]
    assert eb["kind"] == "discrete_argmax"
    assert eb["bit_exact_argmax"] is True
    assert eb["rows_repaired"] >= 1  # the planted near-ties forced repairs
    assert raw["q_table"].dtype == np.int8
    # Dequantized load reconstructs floats through the recorded scales.
    _, deq = load_policy_bundle(q_dir)
    assert deq["q_table"].dtype == np.float32
    # int8 bundles are ~4x smaller than f32 on disk.
    assert manifest["param_bytes"] * 4 <= manifest["param_count"] * 4 + 4


def test_int8_tabular_argmax_repair_exhaustive(tmp_path):
    """The repair pass guarantees argmax equality over the WHOLE table, not
    just sampled observations."""
    cfg = _cfg("tabular")
    ps = _state(cfg)
    _, q_dir = _export_pair(cfg, ps, str(tmp_path))
    _, deq = load_policy_bundle(q_dir)
    np.testing.assert_array_equal(
        np.argmax(np.asarray(ps.q_table), axis=-1),
        np.argmax(deq["q_table"], axis=-1),
    )


@pytest.mark.parametrize("share", [False, True])
def test_int8_continuous_ulp_recorded(share, tmp_path):
    cfg = _cfg("ddpg", ddpg=DDPGConfig(share_across_agents=share))
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    if share:
        # The shared-actor bundle path exports the bare shared params the
        # way the CLI does for share-agents checkpoints.
        from p2pmicrogrid_tpu.models.ddpg import ddpg_params_init

        ps = ddpg_params_init(cfg.ddpg, cfg.sim.n_agents, jax.random.PRNGKey(0))
    q_dir = export_policy_bundle(
        cfg, ps, os.path.join(str(tmp_path), "int8"), dtype="int8"
    )
    manifest, _ = load_policy_bundle(q_dir)
    eb = manifest["quant"]["error_bound"]
    assert eb["kind"] == "continuous_ulp"
    assert 0 <= eb["max_ulp"] <= eb["ulp_budget"] == DEFAULT_ULP_BUDGET
    assert eb["max_abs_action_err"] >= 0.0


def test_int8_export_refuses_tied_dqn(tmp_path):
    """A DQN whose calibration argmax flips under quantization is REFUSED at
    export (it cannot be repaired row-wise) — the contract fails loudly
    instead of shipping a bundle that serves different actions."""
    cfg = _cfg("dqn")
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    # Fresh-init nets carry near-tied actions at some calibration points;
    # if this seed happens to be decisive, force a tie by zeroing the
    # action-input row (all actions then share near-identical Q values and
    # int8 noise flips first-occurrence winners).
    k = np.asarray(ps.online["Dense_0"]["kernel"]).copy()
    k[:, -1, :] *= 1e-6
    online = dict(ps.online)
    online["Dense_0"] = dict(online["Dense_0"], kernel=k)
    ps = ps._replace(online=online)
    with pytest.raises(ValueError, match="bit-exact argmax"):
        export_policy_bundle(
            cfg, ps, os.path.join(str(tmp_path), "int8"), dtype="int8"
        )


def test_int8_export_refuses_over_budget(tmp_path):
    cfg = _cfg("ddpg")
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="error budget"):
        export_policy_bundle(
            cfg, ps, os.path.join(str(tmp_path), "int8"),
            dtype="int8", ulp_budget=0.0,
        )


def test_promotion_gate_refuses_over_budget_candidate(tmp_path):
    """A quantized candidate whose recorded max_ulp exceeds the gate's
    enforced budget is refused BEFORE any eval/SLO work."""
    from p2pmicrogrid_tpu.serve.promotion import GateBudgets, run_promotion_gate

    cfg = _cfg("ddpg")
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    inc_dir = export_policy_bundle(cfg, ps, os.path.join(str(tmp_path), "inc"))
    cand_dir = export_policy_bundle(
        cfg, ps, os.path.join(str(tmp_path), "cand"), dtype="int8"
    )
    # Tighten the enforced budget below the recorded measurement.
    manifest = json.load(open(os.path.join(cand_dir, "manifest.json")))
    measured = manifest["quant"]["error_bound"]["max_ulp"]
    assert measured > 0
    verdict = run_promotion_gate(
        cfg, cand_dir, inc_dir,
        budgets=GateBudgets(max_quant_ulp=measured / 2.0),
        s_eval=2, bench_requests=8,
        service_time_fn=lambda i, j: 0.001,
    )
    assert not verdict.passed
    assert any("max ulp" in r for r in verdict.reasons)

    # An un-tampered budget does NOT add a quant reason (the candidate may
    # still fail the beat-the-incumbent check — same params tie).
    verdict_ok = run_promotion_gate(
        cfg, cand_dir, inc_dir, s_eval=2, bench_requests=8,
        service_time_fn=lambda i, j: 0.001,
    )
    assert not any("ulp" in r for r in verdict_ok.reasons)


def test_promotion_gate_refuses_uncertified_discrete_quant(tmp_path):
    from p2pmicrogrid_tpu.serve.promotion import run_promotion_gate

    cfg = _cfg("tabular")
    ps = _state(cfg)
    inc_dir = export_policy_bundle(cfg, ps, os.path.join(str(tmp_path), "inc"))
    cand_dir = export_policy_bundle(
        cfg, ps, os.path.join(str(tmp_path), "cand"), dtype="int8"
    )
    mpath = os.path.join(cand_dir, "manifest.json")
    manifest = json.load(open(mpath))
    manifest["quant"]["error_bound"]["bit_exact_argmax"] = False
    json.dump(manifest, open(mpath, "w"))
    verdict = run_promotion_gate(
        cfg, cand_dir, inc_dir, s_eval=2, bench_requests=8,
        service_time_fn=lambda i, j: 0.001,
    )
    assert not verdict.passed
    assert any("bit-exact greedy argmax" in r for r in verdict.reasons)


def test_promotion_gate_refuses_stripped_quant_block(tmp_path):
    """An int8 candidate whose quant block was deleted outright (so nothing
    certifies the contract and the loader cannot dequantize) is refused."""
    from p2pmicrogrid_tpu.serve.promotion import run_promotion_gate

    cfg = _cfg("tabular")
    ps = _state(cfg)
    inc_dir = export_policy_bundle(cfg, ps, os.path.join(str(tmp_path), "inc"))
    cand_dir = export_policy_bundle(
        cfg, ps, os.path.join(str(tmp_path), "cand"), dtype="int8"
    )
    mpath = os.path.join(cand_dir, "manifest.json")
    manifest = json.load(open(mpath))
    del manifest["quant"]
    json.dump(manifest, open(mpath, "w"))
    verdict = run_promotion_gate(
        cfg, cand_dir, inc_dir, s_eval=2, bench_requests=8,
        service_time_fn=lambda i, j: 0.001,
    )
    assert not verdict.passed
    assert any("no quant block" in r for r in verdict.reasons)


def test_aot_bucket_cache_warm_swap(tmp_path):
    """Export-time AOT compiles populate the process-wide program cache; a
    fresh same-architecture engine's warmup adopts them without compiling
    (the gateway hot-swap path), and serves bit-identically."""
    clear_aot_program_cache()
    try:
        cfg = _cfg("tabular")
        ps = _state(cfg)
        bundle = export_policy_bundle(
            cfg, ps, os.path.join(str(tmp_path), "b"), aot_buckets=[1, 4],
        )
        manifest = json.load(open(os.path.join(bundle, "manifest.json")))
        assert manifest["aot"]["buckets"] == [1, 4]

        eng = PolicyEngine(bundle_dir=bundle, max_batch=4, device="default")
        warmed = eng.warmup([1, 4], include_step=False)
        assert warmed == [1, 4]
        assert eng.stats["aot_hits"] == 2
        assert eng.stats["aot_compiles"] == 0

        # A cold engine of a DIFFERENT architecture still compiles.
        cfg2 = default_config(
            sim=SimConfig(n_agents=A + 1),
            train=TrainConfig(implementation="tabular"),
        )
        ps2 = init_policy_state(cfg2, jax.random.PRNGKey(0))
        b2 = export_policy_bundle(cfg2, ps2, os.path.join(str(tmp_path), "b2"))
        eng2 = PolicyEngine(bundle_dir=b2, max_batch=4, device="default")
        eng2.warmup([4], include_step=False)
        assert eng2.stats["aot_compiles"] == 1

        obs = calibration_obs(4, A, seed=9)
        eng_cold = PolicyEngine(bundle_dir=bundle, max_batch=4, device="default")
        np.testing.assert_array_equal(eng.act(obs), eng_cold.act(obs))
    finally:
        clear_aot_program_cache()
