"""On-device scenario synthesis + chunked aggregate-scenario training
(the transport and update scheme behind the 10k-scenario north star)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.parallel import (
    device_episode_arrays,
    device_scenario_traces,
    init_scen_state_only,
    train_scenarios_chunked,
)
from p2pmicrogrid_tpu.parallel.scenarios import make_shared_episode_fn
from p2pmicrogrid_tpu.train import make_policy


# Whole module is compile-heavy (chunked-trainer episode compiles (multi-second each)).
pytestmark = pytest.mark.slow

def _cfg(impl="tabular", S=2, A=3, **kw):
    return default_config(
        sim=SimConfig(n_agents=A, n_scenarios=S),
        train=TrainConfig(implementation=impl),
        ddpg=DDPGConfig(buffer_size=32, batch_size=2, share_across_agents=True),
        **kw,
    )


class TestDeviceGen:
    def test_trace_shapes_and_ranges(self):
        t, t_out, load, pv = device_scenario_traces(jax.random.PRNGKey(0), 4)
        assert t.shape == (96,)
        assert t_out.shape == (4, 96)
        assert load.shape == (4, 96, 5)
        assert pv.shape == (4, 96)
        # Shared slot grid (the invariant stack_scenario_arrays asserts).
        np.testing.assert_allclose(np.asarray(t), np.arange(96) / 96, atol=1e-6)
        # Per-scenario max-normalization (dataset.py:47-49).
        assert np.asarray(load).max() <= 1.0 + 1e-6
        assert np.asarray(load).min() > 0.0
        np.testing.assert_allclose(np.asarray(load).max(axis=1), 1.0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(pv).max(axis=1), 1.0, atol=1e-5)
        assert np.asarray(pv).min() >= 0.0
        # October-ish outdoor temperatures.
        assert 0.0 < np.asarray(t_out).mean() < 20.0
        # Scenarios are distinct draws.
        assert not np.allclose(np.asarray(load[0]), np.asarray(load[1]))

    def test_episode_arrays_pairing_and_ratings(self):
        cfg = _cfg(S=3, A=4)
        ratings = make_ratings(cfg, np.random.default_rng(0))
        arrs = device_episode_arrays(
            cfg, jax.random.PRNGKey(1), ratings, 3
        )
        assert arrs.load_w.shape == (3, 96, 4)
        # next_* is the np.roll pairing along time (dataset.py:98-103).
        np.testing.assert_allclose(
            np.asarray(arrs.next_load_w),
            np.roll(np.asarray(arrs.load_w), -1, axis=1),
            rtol=1e-6,
        )
        # Ratings denormalization: agent axis scales match (agent i uses
        # profile i % 5 scaled by its W rating; community.py:219-224).
        assert np.asarray(arrs.load_w[:, :, 0]).max() <= ratings.load_rating_w[0] * (
            1.0 + 1e-5
        )

    def test_jits_inside_episode_program(self):
        cfg = _cfg(S=2, A=3)
        ratings = make_ratings(cfg, np.random.default_rng(0))
        fn = jax.jit(
            lambda k: device_episode_arrays(cfg, k, ratings, 2).load_w.sum()
        )
        assert np.isfinite(float(fn(jax.random.PRNGKey(0))))


class TestChunkedTraining:
    @pytest.mark.parametrize("impl", ["tabular", "ddpg"])
    def test_runs_and_learns(self, impl):
        cfg = _cfg(impl=impl)
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_state

        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        out, rewards, losses, secs = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=2, n_chunks=3,
        )
        # K * S per-scenario records per episode.
        assert rewards.shape == (2, 6)
        assert np.isfinite(rewards).all()
        # Parameters moved.
        before = jax.tree_util.tree_leaves(ps)[0]
        after = jax.tree_util.tree_leaves(out)[0]
        assert not np.allclose(np.asarray(before), np.asarray(after))

    def test_identical_chunks_average_to_single_chunk(self):
        """θ₀ + mean_c(θ_c − θ₀) with identical chunks must equal the one-
        chunk result — the delta-averaging identity behind chunk-gradient
        accumulation."""
        cfg = _cfg(impl="tabular")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_state

        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        # Collapse every chunk onto one draw: the key ignores the chunk index.
        same_key = lambda k, e, c: jax.random.fold_in(k, e)
        one, _, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(7),
            n_episodes=1, n_chunks=1, chunk_key_fn=same_key,
        )
        many, _, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(7),
            n_episodes=1, n_chunks=4, chunk_key_fn=same_key,
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(one), jax.tree_util.tree_leaves(many)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            )

    def test_distinct_chunks_differ_from_single(self):
        cfg = _cfg(impl="tabular")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_state

        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        one, _, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(7),
            n_episodes=1, n_chunks=1,
        )
        many, _, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(7),
            n_episodes=1, n_chunks=3,
        )
        one_l = np.concatenate(
            [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(one)]
        )
        many_l = np.concatenate(
            [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(many)]
        )
        assert not np.allclose(one_l, many_l)

    @pytest.mark.parametrize("impl", ["tabular", "ddpg", "dqn"])
    def test_chunk_parallel_matches_sequential(self, impl):
        """chunk_parallel=C runs the SAME per-chunk trajectories (same key
        chain) through a vmapped episode program — params must match the
        C=1 runner up to delta-summation order, and the per-chunk reward
        records must match in chunk order. dqn additionally exercises the
        per-chunk record-only replay warmup scan under the vmap."""
        cfg = _cfg(impl=impl)
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_state

        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        seq, r_seq, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(7),
            n_episodes=1, n_chunks=4,
        )
        par, r_par, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(7),
            n_episodes=1, n_chunks=4, chunk_parallel=2,
        )
        np.testing.assert_allclose(r_par, r_seq, rtol=1e-5, atol=1e-6)
        for a, b in zip(
            jax.tree_util.tree_leaves(seq), jax.tree_util.tree_leaves(par)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    def test_chunk_parallel_must_divide(self):
        from p2pmicrogrid_tpu.parallel.scenarios import (
            make_chunked_episode_runner,
        )

        cfg = _cfg(impl="tabular")
        with pytest.raises(ValueError, match="chunk_parallel"):
            make_chunked_episode_runner(
                cfg, lambda c, k: (c, (None, None)), 3, chunk_parallel=2
            )

    def test_ddpg_adam_count_dtype_preserved(self):
        """Delta averaging must not float-ify Adam's int step counters."""
        cfg = _cfg(impl="ddpg")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        from p2pmicrogrid_tpu.parallel import init_shared_state

        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        out, _, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=1, n_chunks=2,
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(ps), jax.tree_util.tree_leaves(out)
        ):
            assert np.asarray(a).dtype == np.asarray(b).dtype


class TestLrAutoScale:
    """The pooled-batch lr rule (scenarios.py:auto_scale_ddpg_lrs): shared
    DDPG lrs shrink as (ref_pooled / pooled)^exp once the pooled update batch
    (batch*S*A) exceeds the calibrated reference pool — the automatic form of
    the round-3 measured divergence fix (LEARNING_chunked_r03.json)."""

    def test_large_pool_scales_down(self):
        from p2pmicrogrid_tpu.parallel.scenarios import (
            DDPG_LR_EXP,
            DDPG_LR_REF_POOLED,
            auto_scale_ddpg_lrs,
            ddpg_pooled_batch,
        )

        cfg = default_config(
            sim=SimConfig(n_agents=100, n_scenarios=64),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(batch_size=4, share_across_agents=True),
        )
        pooled = ddpg_pooled_batch(cfg)
        assert pooled == 4 * 64 * 100
        scaled = auto_scale_ddpg_lrs(cfg)
        expect = (DDPG_LR_REF_POOLED / pooled) ** DDPG_LR_EXP
        assert scaled.ddpg.actor_lr == pytest.approx(cfg.ddpg.actor_lr * expect)
        assert scaled.ddpg.critic_lr == pytest.approx(
            cfg.ddpg.critic_lr * expect
        )
        # The critic/actor ratio (reference rl.py:596-597) is preserved.
        assert scaled.ddpg.critic_lr / scaled.ddpg.actor_lr == pytest.approx(
            cfg.ddpg.critic_lr / cfg.ddpg.actor_lr
        )

    def test_small_pool_unchanged(self):
        from p2pmicrogrid_tpu.parallel.scenarios import auto_scale_ddpg_lrs

        cfg = default_config(
            sim=SimConfig(n_agents=2, n_scenarios=2),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(batch_size=4, share_across_agents=True),
        )
        assert auto_scale_ddpg_lrs(cfg) is cfg

    def test_per_agent_pool_has_no_agent_factor(self):
        from p2pmicrogrid_tpu.parallel.scenarios import ddpg_pooled_batch

        cfg = default_config(
            sim=SimConfig(n_agents=100, n_scenarios=64),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(batch_size=4, share_across_agents=False),
        )
        assert ddpg_pooled_batch(cfg) == 4 * 64

    def test_opt_out_and_non_ddpg_untouched(self):
        from p2pmicrogrid_tpu.parallel.scenarios import auto_scale_ddpg_lrs

        pinned = default_config(
            sim=SimConfig(n_agents=100, n_scenarios=64),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(
                batch_size=4, share_across_agents=True, lr_auto_scale=False
            ),
        )
        assert auto_scale_ddpg_lrs(pinned) is pinned
        tab = _cfg(impl="tabular", S=64, A=100)
        assert auto_scale_ddpg_lrs(tab) is tab

    def test_episode_fn_bakes_scaled_lrs(self):
        """Two identically-seeded single-episode runs, one with the rule and
        one pinned at the rule's output lrs, must produce identical params —
        proof the episode program actually consumed the scaled lrs."""
        from p2pmicrogrid_tpu.parallel import init_shared_state
        from p2pmicrogrid_tpu.parallel.scenarios import (
            auto_scale_ddpg_lrs,
            train_scenarios_shared,
        )

        S, A = 80, 5  # pooled = 8*80*5 = 3200 > DDPG_LR_REF_POOLED (400)
        import dataclasses

        base = default_config(
            sim=SimConfig(n_agents=A, n_scenarios=S),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(buffer_size=16, batch_size=8,
                            share_across_agents=True),
        )
        scaled_cfg = auto_scale_ddpg_lrs(base)
        assert scaled_cfg.ddpg.actor_lr < base.ddpg.actor_lr
        pinned = dataclasses.replace(
            base,
            ddpg=dataclasses.replace(
                base.ddpg,
                actor_lr=scaled_cfg.ddpg.actor_lr,
                critic_lr=scaled_cfg.ddpg.critic_lr,
                actor_delay_updates=scaled_cfg.ddpg.actor_delay_updates,
                lr_auto_scale=False,
            ),
        )
        ratings = make_ratings(base, np.random.default_rng(0))
        policy = make_policy(base)
        from p2pmicrogrid_tpu.parallel import stack_scenario_arrays
        from p2pmicrogrid_tpu.parallel.scenarios import make_scenario_traces

        traces = make_scenario_traces(base, S)
        arrays = stack_scenario_arrays(base, traces, ratings)
        outs = []
        for cfg in (base, pinned):
            ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
            out, _, _, _, _ = train_scenarios_shared(
                cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(1),
                n_episodes=1, replay_s=scen,
            )
            outs.append(out)
        for a, b in zip(
            jax.tree_util.tree_leaves(outs[0]), jax.tree_util.tree_leaves(outs[1])
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestLearnBatchCap:
    """DDPGConfig.learn_batch_cap: agent-shared pooled updates larger than
    the cap subsample (slot, scenario, agent) triples straight from the
    replay rings — an unbiased minibatch estimator whose HBM traffic scales
    with the cap, not the batch*S*A pool (_ddpg_update_shared)."""

    def _shared_cfg(self, cap, S=20, A=4, B=8):
        return default_config(
            sim=SimConfig(n_agents=A, n_scenarios=S),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(
                buffer_size=16, batch_size=B, share_across_agents=True,
                learn_batch_cap=cap,
            ),
        )

    def _one_episode(self, cfg):
        from p2pmicrogrid_tpu.parallel import (
            init_shared_state,
            stack_scenario_arrays,
        )
        from p2pmicrogrid_tpu.parallel.scenarios import (
            make_scenario_traces,
            train_scenarios_shared,
        )

        S = cfg.sim.n_scenarios
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        traces = make_scenario_traces(cfg, S)
        arrays = stack_scenario_arrays(cfg, traces, ratings)
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
        out, _, rewards, losses, _ = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(1),
            n_episodes=1, replay_s=scen,
        )
        return out, np.asarray(losses), np.asarray(rewards)

    def test_effective_pool_caps_in_shared_mode_only(self):
        from p2pmicrogrid_tpu.parallel.scenarios import ddpg_pooled_batch

        capped = self._shared_cfg(cap=100)  # pool 8*20*4 = 640
        assert ddpg_pooled_batch(capped) == 100
        uncapped = self._shared_cfg(cap=None)
        assert ddpg_pooled_batch(uncapped) == 640
        import dataclasses

        per_agent = dataclasses.replace(
            capped, ddpg=dataclasses.replace(
                capped.ddpg, share_across_agents=False, learn_batch_cap=100
            )
        )
        # Per-agent pools are batch*S per agent and never capped.
        assert ddpg_pooled_batch(per_agent) == 8 * 20

    def test_cap_above_pool_is_exact_noop(self):
        """A cap the pool never reaches must leave the program bit-identical
        to the uncapped one (the capped branch is static)."""
        out_none, losses_none, _ = self._one_episode(self._shared_cfg(None))
        out_big, losses_big, _ = self._one_episode(self._shared_cfg(1 << 30))
        for a, b in zip(
            jax.tree_util.tree_leaves(out_none), jax.tree_util.tree_leaves(out_big)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(losses_none, losses_big)

    def test_capped_update_runs_finite_and_differs(self):
        out_cap, losses_cap, rewards_cap = self._one_episode(
            self._shared_cfg(100)
        )
        out_full, losses_full, _ = self._one_episode(self._shared_cfg(None))
        assert losses_cap.shape == losses_full.shape  # real per-scenario [S]
        assert np.isfinite(losses_cap).all()
        assert np.isfinite(rewards_cap).all()
        flat = lambda t: np.concatenate(
            [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(t)]
        )
        a, b = flat(out_cap), flat(out_full)
        assert np.isfinite(a).all()
        assert not np.allclose(a, b)

    def test_stripe_count_degrades_gracefully(self):
        """A cap that is not a multiple of 8 must keep multiple stripes
        (largest divisor <= 8), not collapse to one contiguous block — a
        single block covers only ~cap/A consecutive scenarios, the
        correlated-draw failure mode the stripes exist to avoid."""
        pick = lambda cap: next(n for n in range(8, 0, -1) if cap % n == 0)
        assert pick(32768) == 8
        assert pick(30000) == 8  # 30000 = 8 * 3750
        assert pick(100) == 5
        assert pick(30002) == 7  # 2 * 7 * ...
        assert pick(97) == 1  # prime: nothing to split evenly
        # And the update itself still runs finite at such a cap.
        _, losses, rewards = self._one_episode(self._shared_cfg(90))
        assert np.isfinite(losses).all() and np.isfinite(rewards).all()

    def test_cap_raises_the_auto_scaled_lrs(self):
        """The lr rule keys on the EFFECTIVE (capped) batch: capping a huge
        pool must leave the lrs at the cap's scale, not the pool's."""
        from p2pmicrogrid_tpu.parallel.scenarios import (
            DDPG_LR_EXP,
            DDPG_LR_REF_POOLED,
            auto_scale_ddpg_lrs,
        )

        big = self._shared_cfg(cap=None, S=64, A=1000, B=4)  # pool 256k
        capped = self._shared_cfg(cap=32768, S=64, A=1000, B=4)
        lr_big = auto_scale_ddpg_lrs(big).ddpg.actor_lr
        lr_cap = auto_scale_ddpg_lrs(capped).ddpg.actor_lr
        assert lr_cap > lr_big
        expect = (DDPG_LR_REF_POOLED / 32768) ** DDPG_LR_EXP
        assert lr_cap == pytest.approx(capped.ddpg.actor_lr * expect)


class TestActorDelay:
    def test_actor_frozen_until_critic_count_then_released(self):
        """Delayed policy updates (DDPGConfig.actor_delay_updates): the
        actor, its optimizer and nothing else hold still until the critic
        has taken N steps; the critic trains throughout."""
        import dataclasses

        from p2pmicrogrid_tpu.config import DDPGConfig
        from p2pmicrogrid_tpu.models.ddpg import (
            ddpg_learn_batch,
            ddpg_params_init,
        )

        d = DDPGConfig(batch_size=4, share_across_agents=True,
                       actor_delay_updates=2)
        p = ddpg_params_init(d, None, jax.random.PRNGKey(0))
        k = jax.random.PRNGKey(1)
        s = jax.random.normal(k, (4, 4))
        a = jax.random.uniform(k, (4, 1))
        r = jax.random.normal(k, (4,))

        def step(p):
            pa, pc, pat, pct, oa, oc, _, _ = ddpg_learn_batch(
                d, p.actor, p.critic, p.actor_target, p.critic_target,
                p.actor_opt, p.critic_opt, s, a, r, s,
            )
            return p._replace(actor=pa, critic=pc, actor_target=pat,
                              critic_target=pct, actor_opt=oa, critic_opt=oc)

        p1 = step(p)   # critic count 1 < 2: actor frozen
        for x, y in zip(jax.tree_util.tree_leaves(p.actor),
                        jax.tree_util.tree_leaves(p1.actor)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        assert not all(
            np.allclose(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree_util.tree_leaves(p.critic),
                            jax.tree_util.tree_leaves(p1.critic))
        )
        p2 = step(p1)  # critic count 2 >= 2: actor released
        assert not all(
            np.allclose(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree_util.tree_leaves(p1.actor),
                            jax.tree_util.tree_leaves(p2.actor))
        )

    def test_auto_rule_leaves_delay_off(self):
        """The rule scales lrs only: the 1000-agent seed sweep measured the
        unlucky-init excursion INVARIANT to the actor delay (identical
        trajectories at 0/2/5 episodes), so defaulting it on would be an
        unsupported claim (artifacts/LEARNING_northstar_seeds_r04.json)."""
        from p2pmicrogrid_tpu.parallel.scenarios import auto_scale_ddpg_lrs

        cfg = default_config(
            sim=SimConfig(n_agents=100, n_scenarios=64),
            train=TrainConfig(implementation="ddpg"),
            ddpg=DDPGConfig(batch_size=4, share_across_agents=True),
        )
        assert auto_scale_ddpg_lrs(cfg).ddpg.actor_delay_updates == 0


class TestChunkedDqnWarmup:
    def test_record_only_pass_fills_replay(self):
        """The warmup mechanism itself: a record_only episode must advance
        the replay count by one full episode of transitions and leave the
        parameters untouched (reference init_buffers semantics)."""
        from p2pmicrogrid_tpu.config import DQNConfig
        from p2pmicrogrid_tpu.parallel import init_shared_state
        from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays

        cfg = default_config(
            sim=SimConfig(n_agents=3, n_scenarios=2),
            train=TrainConfig(implementation="dqn"),
            dqn=DQNConfig(buffer_size=200, batch_size=2, warmup_passes=1),
        )
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
        warmup_fn = make_shared_episode_fn(
            cfg, policy, None, ratings,
            arrays_fn=lambda k: device_episode_arrays(cfg, k, ratings, 2),
            n_scenarios=2, record_only=True,
        )
        (ps2, scen2), _ = warmup_fn((ps, scen), jax.random.PRNGKey(1))
        assert int(scen.count) == 0
        assert int(scen2.count) == cfg.sim.slots_per_day
        for a, b in zip(
            jax.tree_util.tree_leaves(ps), jax.tree_util.tree_leaves(ps2)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_warmup_changes_training_and_stays_finite(self):
        """The default chunked-DQN path warms each chunk's fresh replay with
        record-only passes (reference init_buffers, community.py:125-147);
        an unwarmed custom-runner run from the same keys must differ."""
        from p2pmicrogrid_tpu.config import DQNConfig
        from p2pmicrogrid_tpu.parallel import init_shared_state
        from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays

        cfg = default_config(
            sim=SimConfig(n_agents=3, n_scenarios=2),
            train=TrainConfig(implementation="dqn"),
            dqn=DQNConfig(buffer_size=16, batch_size=2, warmup_passes=1),
        )
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))

        warmed, _, losses, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=1, n_chunks=2,
        )
        assert np.isfinite(losses).all()

        # Same keys, runner WITHOUT warmup: different replay contents at the
        # early slots -> different parameters out.
        from p2pmicrogrid_tpu.parallel.scenarios import (
            make_chunked_episode_runner,
        )

        episode_fn = make_shared_episode_fn(
            cfg, policy, None, ratings,
            arrays_fn=lambda k: device_episode_arrays(cfg, k, ratings, 2),
            n_scenarios=2,
        )
        runner = make_chunked_episode_runner(cfg, episode_fn, 2)
        unwarmed, _, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=1, n_chunks=2, episode_fn=episode_fn, runner=runner,
        )
        w = np.concatenate(
            [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(warmed)]
        )
        u = np.concatenate(
            [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(unwarmed)]
        )
        assert not np.allclose(w, u)


class TestChunkedOnMesh:
    def test_sharded_chunked_matches_unsharded(self):
        """The chunked north star's multi-chip path: constraining the
        on-device generated scenario arrays to the mesh must change placement
        only, not the math."""
        from p2pmicrogrid_tpu.parallel import init_shared_state
        from p2pmicrogrid_tpu.parallel.mesh import make_mesh, scenario_sharding

        cfg = _cfg(impl="ddpg", S=8, A=3)
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        sh = scenario_sharding(make_mesh())

        sharded, _, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=1, n_chunks=2, scenario_sharding=sh,
        )
        single, _, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=1, n_chunks=2,
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(sharded), jax.tree_util.tree_leaves(single)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )

    def test_sharded_run_records_mesh_identity_and_counters(self, tmp_path):
        """A sharded chunked run with telemetry records the MESH identity
        (shape + axis names, not just a device count) in its manifest, and
        its in-scan counter totals — all-reduced across that mesh inside
        the jitted program — reach the sink (ISSUE 3 / ROADMAP multi-host
        aggregation item)."""
        from p2pmicrogrid_tpu.parallel import init_shared_state
        from p2pmicrogrid_tpu.parallel.mesh import make_mesh, scenario_sharding
        from p2pmicrogrid_tpu.telemetry import MemorySink, Telemetry

        cfg = _cfg(impl="tabular", S=8, A=3)
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        mesh = make_mesh()
        sink = MemorySink()
        tel = Telemetry(run_id="mesh-run", sinks=[sink])
        train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=1, n_chunks=2,
            scenario_sharding=scenario_sharding(mesh), telemetry=tel,
        )
        assert tel.manifest["mesh_shape"] == [mesh.devices.size]
        assert tel.manifest["mesh_axis_names"] == ["data"]
        dc_events = [
            r for r in sink.records if r.get("kind") == "device_counters"
        ]
        assert len(dc_events) == 1
        assert dc_events[0]["market_residual_wh"] > 0.0

    def test_sharded_composes_with_chunk_parallel(self):
        """scenario_sharding (each chunk's scenario axis over the mesh) and
        chunk_parallel (C chunks vmapped side by side) are orthogonal axes of
        the same runner — together they must still change placement only."""
        from p2pmicrogrid_tpu.parallel import init_shared_state
        from p2pmicrogrid_tpu.parallel.mesh import make_mesh, scenario_sharding

        cfg = _cfg(impl="ddpg", S=8, A=3)
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        sh = scenario_sharding(make_mesh())

        both, r_both, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=1, n_chunks=4, scenario_sharding=sh, chunk_parallel=2,
        )
        plain, r_plain, _, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=1, n_chunks=4,
        )
        np.testing.assert_allclose(r_both, r_plain, rtol=1e-5, atol=1e-6)
        for a, b in zip(
            jax.tree_util.tree_leaves(both), jax.tree_util.tree_leaves(plain)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            )


class TestTrainingDeviceCounters:
    """Satellite of the serving PR: chunked TRAINING episodes report the
    in-scan device counters + replay saturation, not just the greedy evals
    (ROADMAP open items)."""

    def test_runner_collects_counters_and_replay_fill(self):
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state
        from p2pmicrogrid_tpu.parallel.scenarios import (
            make_chunked_episode_runner,
        )
        from p2pmicrogrid_tpu.telemetry import dc_to_dict

        cfg = _cfg(impl="ddpg")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        episode_fn = make_shared_episode_fn(
            cfg, policy, None, ratings,
            arrays_fn=lambda k: device_episode_arrays(
                cfg, k, ratings, cfg.sim.n_scenarios
            ),
            n_scenarios=cfg.sim.n_scenarios, collect_device_metrics=True,
        )
        runner = make_chunked_episode_runner(
            cfg, episode_fn, n_chunks=2, collect_device_metrics=True
        )
        keys = jnp.stack([jax.random.PRNGKey(i) for i in (1, 2)])
        out = runner(ps, keys)
        assert len(out) == 5
        _, r, l, dc, fills = out
        assert r.shape == (2 * cfg.sim.n_scenarios,)
        d = dc_to_dict(dc)
        assert d["nonfinite_q"] == 0 and d["nonfinite_loss"] == 0
        assert d["market_residual_wh"] > 0.0
        # Each chunk ran 96 slots into a 32-capacity ring: saturated.
        fills = np.asarray(fills)
        assert fills.shape == (2,)
        assert np.all(fills == 1.0)

    def test_train_scenarios_chunked_emits_telemetry(self):
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state
        from p2pmicrogrid_tpu.telemetry import MemorySink, Telemetry

        cfg = _cfg(impl="ddpg")
        ratings = make_ratings(cfg, np.random.default_rng(0))
        policy = make_policy(cfg)
        ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        sink = MemorySink()
        tel = Telemetry(run_id="t", sinks=[sink])
        train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=2, n_chunks=2, telemetry=tel,
        )
        events = [r for r in sink.records if r.get("kind") == "device_counters"]
        assert len(events) == 2
        assert all(e["phase"] == "train" for e in events)
        assert all("replay_fill_fraction" in e for e in events)
        s = tel.summary()
        assert "device.comfort_violations" in s["counters"]
        assert s["gauges"]["replay.fill_fraction"] == 1.0
