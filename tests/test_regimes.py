"""Scenario regime engine (ISSUE 13): tier-1 acceptance.

Market-mechanism equivalences (symmetric bids reduce bit-for-bit to the
midpoint rule) and per-slot conservation across all mechanisms, islanding
zero-grid clearing, EV deadline constraints, the neutral-regime bitwise
identity with the plain shared episode program, the single-compile
mixed-regime batch (no per-regime retrace), trainer integration
(shared/independent/chunked), the fused-path loud refusal, the promotion
gate's per-regime no-regression rule, the warehouse --regimes view, and
the REGIME_*.jsonl capture schema. JAX_PLATFORMS=cpu-safe and fast.
"""

import json
import os
import sqlite3
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.ops.auction import (
    MECH_DOUBLE_AUCTION,
    MECH_MIDPOINT,
    MECH_UNIFORM,
    double_auction_price,
    mechanism_trade_price,
    trade_volumes,
    uniform_clearing_price,
)
from p2pmicrogrid_tpu.ops.tariff import p2p_price
from p2pmicrogrid_tpu.parallel import (
    init_shared_state,
    make_scenario_traces,
    stack_scenario_arrays,
)
from p2pmicrogrid_tpu.parallel.scenarios import make_shared_episode_fn
from p2pmicrogrid_tpu.regimes import (
    REGIME_LIBRARY,
    RegimeSpec,
    apply_weather_regimes,
    build_portfolio,
    ev_charge_step,
    init_ev_need,
    make_regime_episode_fn,
    make_regime_eval,
    regime_slot_batched,
    resolve_specs,
)
from p2pmicrogrid_tpu.train import make_policy

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

import check_artifacts_schema as schema  # noqa: E402


def _cfg(n_agents=3, n_scenarios=4, impl="tabular", **sim_kw):
    return default_config(
        sim=SimConfig(n_agents=n_agents, n_scenarios=n_scenarios, **sim_kw),
        train=TrainConfig(implementation=impl),
    )


@pytest.fixture(scope="module")
def world():
    """Shared cfg/ratings/arrays/policy for the episode-program tests."""
    cfg = _cfg()
    ratings = make_ratings(cfg, np.random.default_rng(42))
    traces = make_scenario_traces(cfg)
    arrays = stack_scenario_arrays(cfg, traces, ratings)
    policy = make_policy(cfg)
    return cfg, ratings, arrays, policy


# -- market mechanisms ---------------------------------------------------------


class TestMechanisms:
    buy = jnp.asarray(np.linspace(0.08, 0.17, 7).astype(np.float32))
    inj = jnp.full((7,), 0.07, dtype=jnp.float32)

    def test_symmetric_bids_reduce_bitwise_to_midpoint(self):
        """The satellite equivalence: a balanced book (symmetric bids) and
        the symmetric spread split k=0.5 reproduce the midpoint rule
        BIT-FOR-BIT, not just approximately."""
        demand = jnp.full((7,), 1234.5, dtype=jnp.float32)
        supply = jnp.full((7,), 1234.5, dtype=jnp.float32)
        mid = p2p_price(self.buy, self.inj)
        da = double_auction_price(self.buy, self.inj, demand, supply, k=0.5)
        up = uniform_clearing_price(self.buy, self.inj, demand, supply)
        assert np.asarray(da).tobytes() == np.asarray(mid).tobytes()
        assert np.asarray(up).tobytes() == np.asarray(mid).tobytes()

    def test_double_auction_k_extremes(self):
        demand = jnp.ones((7,))
        supply = jnp.ones((7,))
        lo = double_auction_price(self.buy, self.inj, demand, supply, k=0.0)
        hi = double_auction_price(self.buy, self.inj, demand, supply, k=1.0)
        np.testing.assert_allclose(np.asarray(lo), np.asarray(self.inj), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(hi), np.asarray(self.buy), rtol=1e-6)

    def test_uniform_price_tilts_toward_scarce_side(self):
        mid = np.asarray(p2p_price(self.buy, self.inj))
        heavy_demand = np.asarray(
            uniform_clearing_price(self.buy, self.inj, 3000.0, 1000.0)
        )
        heavy_supply = np.asarray(
            uniform_clearing_price(self.buy, self.inj, 1000.0, 3000.0)
        )
        assert (heavy_demand > mid).all()
        assert (heavy_supply < mid).all()
        assert (heavy_demand <= np.asarray(self.buy) + 1e-9).all()
        assert (heavy_supply >= np.asarray(self.inj) - 1e-9).all()

    def test_mixed_batch_dispatch_elementwise(self):
        buy = jnp.asarray([0.15, 0.15, 0.15], dtype=jnp.float32)
        inj = jnp.asarray([0.07, 0.07, 0.07], dtype=jnp.float32)
        demand = jnp.asarray([900.0, 900.0, 900.0])
        supply = jnp.asarray([300.0, 300.0, 300.0])
        mech = jnp.asarray(
            [MECH_MIDPOINT, MECH_DOUBLE_AUCTION, MECH_UNIFORM],
            dtype=jnp.int32,
        )
        out = np.asarray(
            mechanism_trade_price(mech, buy, inj, demand, supply, 0.8)
        )
        assert out[0] == np.asarray(p2p_price(buy, inj))[0]
        assert out[1] == np.asarray(
            double_auction_price(buy, inj, demand, supply, 0.8)
        )[1]
        assert out[2] == np.asarray(
            uniform_clearing_price(buy, inj, demand, supply)
        )[2]

    def test_trade_volumes(self):
        p2p = jnp.asarray([[100.0, -40.0, 0.0], [-10.0, 20.0, 30.0]])
        d, s = trade_volumes(p2p)
        np.testing.assert_allclose(np.asarray(d), [100.0, 50.0])
        np.testing.assert_allclose(np.asarray(s), [40.0, 10.0])


# -- regime slot physics -------------------------------------------------------


def _tiled_arrays(cfg_one, ratings, n):
    """One scenario draw tiled to n identical scenarios — isolates the
    regime axis (every scenario sees the same physics)."""
    traces = make_scenario_traces(cfg_one, n_scenarios=1)
    arrays1 = stack_scenario_arrays(
        cfg_one.replace(sim=SimConfig(
            n_agents=cfg_one.sim.n_agents, n_scenarios=1
        )), traces, ratings,
    )
    tile = lambda x: jnp.tile(x, (n,) + (1,) * (x.ndim - 1))
    return jax.tree_util.tree_map(tile, arrays1)


@pytest.fixture(scope="module")
def slot_outputs():
    """Per-slot outputs of one greedy episode over 5 IDENTICAL scenarios
    assigned to: midpoint, double_auction, uniform_price, islanding_noon,
    dr_spike. Shared by the conservation/islanding/event tests."""
    # rounds=0 (single decision pass, equal-split book): the reference's
    # proportional negotiation branch degenerates to zero matches for
    # one-buyer/many-seller books at rounds>=1, and the price-
    # differentiation assertion below needs actual trades.
    cfg = _cfg(n_agents=3, n_scenarios=5, rounds=0)
    ratings = make_ratings(cfg, np.random.default_rng(42))
    arrays = _tiled_arrays(cfg, ratings, 5)
    # Agent 0 loses its rooftop PV so the midday P2P book is two-sided —
    # agents 1-2 run a solar surplus while agent 0 buys; the mask is
    # identical across scenarios, so mechanism-independence still holds.
    pv_mask = jnp.asarray([0.0, 1.0, 1.0], dtype=jnp.float32)
    arrays = arrays._replace(
        pv_w=arrays.pv_w * pv_mask, next_pv_w=arrays.next_pv_w * pv_mask
    )
    policy = make_policy(cfg)
    ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
    pf = build_portfolio(
        ["baseline", "double_auction", "uniform_price", "islanding_noon",
         "dr_spike"],
        5,
        assignment=np.arange(5),
    )
    from p2pmicrogrid_tpu.envs.community import AgentRatings, init_physical

    ratings_j = AgentRatings(*(jnp.asarray(a) for a in ratings))
    rp = pf.scenario_params

    @jax.jit
    def greedy_episode(pol_state, key):
        k_phys, k_scan = jax.random.split(key)
        # One shared physical init tiled over scenarios: identical physics.
        phys1 = init_physical(cfg, k_phys)
        phys = jax.tree_util.tree_map(
            lambda x: jnp.tile(x[None], (5, 1)), phys1
        )
        xs = jax.tree_util.tree_map(lambda x: jnp.swapaxes(x, 0, 1), arrays)
        xs = (xs.time, xs.t_out, xs.load_w, xs.pv_w,
              xs.next_time, xs.next_load_w, xs.next_pv_w)
        ev0 = init_ev_need(rp, cfg.sim.n_agents)

        def slot(carry, xs_t):
            phys_s, ev_need, kk = carry
            kk, k_act = jax.random.split(kk)
            phys_s, _, out, _, _, ev_need, extras = regime_slot_batched(
                cfg, policy, pol_state, phys_s, ev_need, xs_t, k_act,
                ratings_j, rp, explore=False,
            )
            return (phys_s, ev_need, kk), (out, extras["curtailed_w"])

        _, (outs, curtailed) = jax.lax.scan(slot, (phys, ev0, k_scan), xs)
        return outs, curtailed

    outs, curtailed = greedy_episode(ps, jax.random.PRNGKey(3))
    return cfg, pf, outs, np.asarray(curtailed)


class TestConservation:
    def test_matching_is_mechanism_independent(self, slot_outputs):
        """Mechanisms set PRICES only: identical scenarios under midpoint /
        double-auction / uniform clearing produce bit-identical physical
        powers (p_grid, p_p2p) — conservation transfers across all three."""
        _, _, outs, _ = slot_outputs
        p_grid = np.asarray(outs.p_grid)   # [T, S, A]
        p_p2p = np.asarray(outs.p_p2p)
        for s in (1, 2):  # double_auction, uniform vs midpoint
            assert np.array_equal(p_grid[:, 0], p_grid[:, s])
            assert np.array_equal(p_p2p[:, 0], p_p2p[:, s])
        # ... but the trade PRICES differ where trades exist.
        tp = np.asarray(outs.trade_price)  # [T, S]
        traded = np.abs(p_p2p).sum(axis=-1) > 0  # [T, S]
        assert (tp[:, 1] != tp[:, 0])[traded[:, 1]].any()
        # The uniform price tilts off midpoint too: it reads the PRE-
        # clearing book (one buyer vs two sellers here — heavy supply), so
        # its imbalance term is live, not pinned at zero by the balanced
        # matched volumes.
        assert (tp[:, 2] != tp[:, 0])[traded[:, 2]].any()

    def test_per_slot_energy_conservation_all_mechanisms(self, slot_outputs):
        """Matched P2P power nets to ~zero across agents every slot, for
        every mechanism: every Watt bought peer-to-peer is a Watt sold."""
        _, _, outs, _ = slot_outputs
        p_p2p = np.asarray(outs.p_p2p)  # [T, S, A]
        scale = np.abs(p_p2p).sum(axis=-1) + 1.0
        np.testing.assert_allclose(
            p_p2p.sum(axis=-1) / scale, 0.0, atol=1e-4
        )

    def test_islanding_clears_with_zero_grid_exchange(self, slot_outputs):
        cfg, pf, outs, curtailed = slot_outputs
        spec = REGIME_LIBRARY["islanding_noon"]
        p_grid = np.asarray(outs.p_grid)  # [T, S, A]
        window = np.arange(spec.outage_start_slot, spec.outage_end_slot)
        outside = np.setdiff1d(np.arange(p_grid.shape[0]), window)
        # Scenario 3 is the islanded one: zero grid exchange inside the
        # window, EXACTLY (masked, not approximately).
        assert (p_grid[window, 3] == 0.0).all()
        # Outside the window it matches the baseline scenario bit-for-bit.
        assert np.array_equal(p_grid[outside, 3], p_grid[outside, 0])
        # The residual the grid would have carried is recorded curtailed
        # (identical physics: the baseline scenario's grid power IS the
        # islanded scenario's curtailment).
        np.testing.assert_allclose(curtailed[window, 3], p_grid[window, 0])

    def test_price_spike_multiplies_buy_price_in_window(self, slot_outputs):
        _, _, outs, _ = slot_outputs
        spec = REGIME_LIBRARY["dr_spike"]
        buy = np.asarray(outs.buy_price)  # [T, S]
        w = slice(spec.spike_start_slot, spec.spike_end_slot)
        np.testing.assert_allclose(
            buy[w, 4], buy[w, 0] * spec.spike_mult, rtol=1e-6
        )
        out_w = np.r_[0:spec.spike_start_slot, spec.spike_end_slot:96]
        assert np.array_equal(buy[out_w, 4], buy[out_w, 0])
        # Islanded scenario's cost >= baseline's (curtailment is billed).
        cost = np.asarray(outs.cost).sum(axis=(0, 2))
        assert cost[4] > cost[0]  # spike regime pays more


class TestWeatherAndEv:
    def test_weather_transform_and_neutral_identity(self, world):
        cfg, ratings, arrays, _ = world
        pf = build_portfolio(["winter", "summer", "baseline", "heatwave"], 4)
        out = apply_weather_regimes(arrays, pf.scenario_params)
        specs = {s.name: s for s in pf.specs}
        np.testing.assert_allclose(
            np.asarray(out.t_out[0]),
            np.asarray(arrays.t_out[0]) + specs["winter"].temp_offset_c,
            rtol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(out.pv_w[1]),
            np.asarray(arrays.pv_w[1]) * specs["summer"].pv_scale,
            rtol=1e-6,
        )
        # Neutral regime (scenario 2: baseline) is the bitwise identity.
        assert np.array_equal(np.asarray(out.t_out[2]), np.asarray(arrays.t_out[2]))
        assert np.array_equal(np.asarray(out.load_w[2]), np.asarray(arrays.load_w[2]))
        # next_* leaves stay the rolled counterparts of the scaled leaves.
        np.testing.assert_allclose(
            np.asarray(out.next_pv_w[3]),
            np.roll(np.asarray(out.pv_w[3]), -1, axis=0),
            rtol=1e-6,
        )

    def test_ev_floor_guarantees_feasible_delivery(self):
        """An idle dial cannot strand the vehicle: stepping the whole
        window at dial=0 still delivers the full need via the
        deadline-feasibility floor."""
        cfg = _cfg(n_agents=2, n_scenarios=1)
        spec = RegimeSpec(
            name="ev", ev_present=True, ev_arrival_slot=72,
            ev_deadline_slot=96, ev_energy_kwh=8.0,
        )
        pf = build_portfolio([spec], 1)
        rp = pf.scenario_params
        need = init_ev_need(rp, 2)
        np.testing.assert_allclose(np.asarray(need), 8.0 * 3.6e6)
        dial = jnp.zeros((1, 2))
        delivered = np.zeros((1, 2))
        for slot in range(96):
            ev_w, need, miss = ev_charge_step(
                cfg, rp, need, jnp.asarray([slot], dtype=jnp.int32), dial
            )
            delivered += np.asarray(ev_w) * cfg.sim.dt_seconds
            assert (np.asarray(ev_w) <= spec.ev_max_power_w + 1e-6).all()
            if slot < 72:
                assert (np.asarray(ev_w) == 0.0).all()
            assert (np.asarray(miss) == 0.0).all()
        np.testing.assert_allclose(delivered, 8.0 * 3.6e6, rtol=1e-5)
        assert (np.asarray(need) == 0.0).all()

    def test_ev_infeasible_window_bills_the_miss(self):
        """A need the window cannot physically deliver surfaces as a
        deadline miss, not silent under-delivery."""
        cfg = _cfg(n_agents=1, n_scenarios=1)
        spec = RegimeSpec(
            name="tight", ev_present=True, ev_arrival_slot=90,
            ev_deadline_slot=92, ev_energy_kwh=20.0,  # 20 kWh in 30 min
        )
        pf = build_portfolio([spec], 1)
        rp = pf.scenario_params
        need = init_ev_need(rp, 1)
        dial = jnp.ones((1, 1))
        total_miss = 0.0
        for slot in range(88, 96):
            ev_w, need, miss = ev_charge_step(
                cfg, rp, need, jnp.asarray([slot], dtype=jnp.int32), dial
            )
            total_miss += float(np.asarray(miss).sum())
        feasible_ws = spec.ev_max_power_w * 2 * cfg.sim.dt_seconds
        np.testing.assert_allclose(
            total_miss, 20.0 * 3.6e6 - feasible_ws, rtol=1e-5
        )
        assert (np.asarray(need) == 0.0).all()  # window closed


# -- episode programs ----------------------------------------------------------


class TestEpisodePrograms:
    def test_neutral_regime_bit_exact_vs_plain_shared(self, world):
        """An all-baseline portfolio reproduces the plain shared episode
        program bit-for-bit (same key chain, same settlement arithmetic):
        the regime engine costs nothing when no regime is active."""
        cfg, ratings, arrays, policy = world
        pf = build_portfolio(["baseline"], 4)
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
        plain = make_shared_episode_fn(cfg, policy, arrays, ratings)
        reg = make_regime_episode_fn(
            cfg, policy, ratings, pf.scenario_params, arrays_s=arrays,
            specs=pf.specs,
        )
        c1, ys1 = plain((ps, scen), jax.random.PRNGKey(7))
        c2, ys2 = reg((ps, scen), jax.random.PRNGKey(7))
        for a, b in zip(
            jax.tree_util.tree_leaves(c1), jax.tree_util.tree_leaves(c2)
        ):
            assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(np.asarray(ys1[0]), np.asarray(ys2[0]))
        assert np.array_equal(np.asarray(ys1[1]), np.asarray(ys2[1]))

    def test_single_compile_mixed_batch_and_portfolio_swap(self, world):
        """The acceptance single-compile check: a 4-regime mixed batch
        runs as ONE compiled program, and swapping to a different
        portfolio of the same shape reuses it — regime fields are array
        leaves, so no per-regime retrace can happen."""
        cfg, ratings, arrays, policy = world
        pf_a = build_portfolio(
            ["winter", "ev_evening", "dr_spike", "double_auction"], 4
        )
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
        fn = make_regime_episode_fn(
            cfg, policy, ratings, pf_a.scenario_params, arrays_s=arrays,
            collect_regime_metrics=True, one_hot=pf_a.one_hot,
            specs=pf_a.specs,
        )
        carry, ys_a = fn((ps, scen), jax.random.PRNGKey(7))
        pf_b = build_portfolio(
            ["summer", "islanding_noon", "uniform_price", "cold_snap"], 4
        )
        fn_b = fn.with_regimes(pf_b.scenario_params)
        _, ys_b = fn_b((ps, scen), jax.random.PRNGKey(7))
        assert fn.jitted._cache_size() == 1
        assert not np.array_equal(np.asarray(ys_a[0]), np.asarray(ys_b[0]))
        # Per-regime counters rode the scan: EV regime charged energy.
        rc = ys_a[2]
        ev_idx = list(pf_a.names).index("ev_evening")
        assert float(np.asarray(rc.ev_charged_wh)[ev_idx]) > 0.0
        assert float(np.asarray(rc.ev_charged_wh).sum()) == pytest.approx(
            float(np.asarray(rc.ev_charged_wh)[ev_idx])
        )

    def test_regime_counters_match_episode_rewards(self, world):
        """rc.reward is the segment-sum of the per-scenario episode
        rewards — the counters attribute exactly what the episode saw."""
        cfg, ratings, arrays, policy = world
        pf = build_portfolio(["winter", "dr_spike"], 4)  # 2 scenarios each
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
        fn = make_regime_episode_fn(
            cfg, policy, ratings, pf.scenario_params, arrays_s=arrays,
            collect_regime_metrics=True, one_hot=pf.one_hot, specs=pf.specs,
        )
        _, (rewards_s, _, rc) = fn((ps, scen), jax.random.PRNGKey(9))
        rewards_s = np.asarray(rewards_s)
        onehot = np.asarray(pf.one_hot)
        np.testing.assert_allclose(
            np.asarray(rc.reward), rewards_s @ onehot, rtol=1e-4
        )

    def test_independent_mode_trains_per_scenario_learners(self, world):
        cfg, ratings, arrays, policy = world
        pf = build_portfolio(["winter", "summer"], 4)
        from p2pmicrogrid_tpu.train import init_policy_state

        ps_s = jax.vmap(lambda k: init_policy_state(cfg, k))(
            jax.random.split(jax.random.PRNGKey(0), 4)
        )
        fn = make_regime_episode_fn(
            cfg, policy, ratings, pf.scenario_params, arrays_s=arrays,
            mode="independent", specs=pf.specs,
        )
        carry, (r, l) = fn(ps_s, jax.random.PRNGKey(7))
        assert r.shape == (4,) and np.isfinite(np.asarray(r)).all()
        q = np.asarray(carry.q_table)  # [S, A, ...]
        # Winter and summer learners saw different worlds: tables differ.
        assert not np.array_equal(q[0], q[1])

    def test_independent_ddpg_refused(self, world):
        cfg, ratings, arrays, _ = world
        cfg_ddpg = cfg.replace(train=TrainConfig(implementation="ddpg"))
        pf = build_portfolio(["baseline"], 4)
        with pytest.raises(ValueError, match="independent regime"):
            make_regime_episode_fn(
                cfg_ddpg, make_policy(cfg_ddpg), ratings,
                pf.scenario_params, arrays_s=arrays, mode="independent",
            )

    def test_shared_trainer_integration(self, world):
        cfg, ratings, arrays, policy = world
        from p2pmicrogrid_tpu.parallel.scenarios import train_scenarios_shared

        pf = build_portfolio(["winter", "ev_evening"], 4)
        ps, scen = init_shared_state(cfg, jax.random.PRNGKey(0))
        fn = make_regime_episode_fn(
            cfg, policy, ratings, pf.scenario_params, arrays_s=arrays,
            specs=pf.specs,
        )
        ps2, scen2, rewards, losses, _ = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(1), 2,
            replay_s=scen, episode_fn=fn, donate=False,
        )
        assert rewards.shape == (2, 4)
        assert np.isfinite(rewards).all()
        assert not np.array_equal(
            np.asarray(ps.q_table), np.asarray(ps2.q_table)
        )

    def test_chunked_trainer_integration_device_gen(self, world):
        """The chunked driver runs regime episodes over DEVICE-generated
        arrays: weather scaling composes with on-device synthesis inside
        one compiled chunk program."""
        cfg, ratings, _, policy = world
        from p2pmicrogrid_tpu.parallel.device_gen import device_episode_arrays
        from p2pmicrogrid_tpu.parallel.scenarios import (
            train_scenarios_chunked,
        )

        pf = build_portfolio(
            ["winter", "summer", "dr_spike", "uniform_price"], 4
        )
        from p2pmicrogrid_tpu.parallel import init_shared_pol_state

        ps = init_shared_pol_state(cfg, jax.random.PRNGKey(0))
        fn = make_regime_episode_fn(
            cfg, policy, ratings, pf.scenario_params,
            arrays_fn=lambda k: device_episode_arrays(cfg, k, ratings, 4),
            n_scenarios=4, specs=pf.specs,
        )
        ps2, rewards, losses, _ = train_scenarios_chunked(
            cfg, policy, ps, ratings, jax.random.PRNGKey(1),
            n_episodes=2, n_chunks=2, episode_fn=fn, donate=False,
        )
        assert rewards.shape == (2, 8)  # K*S
        assert np.isfinite(rewards).all()

    def test_fused_refusal_is_loud_and_actionable(self, world):
        cfg, ratings, arrays, policy = world
        pf = build_portfolio(["ev_evening", "islanding_noon"], 4)
        with pytest.raises(ValueError) as err:
            make_regime_episode_fn(
                cfg, policy, ratings, pf.scenario_params, arrays_s=arrays,
                fused=True, specs=pf.specs,
            )
        msg = str(err.value)
        assert "EV load" in msg and "islanding masks" in msg
        assert "fused" in msg and "baseline world" in msg

    def test_fused_slot_config_refused_too(self, world):
        """SimConfig.fused_slot=True must refuse through the same path —
        the config knob cannot reach silently-wrong fused output."""
        cfg, ratings, arrays, policy = world
        cfg_fused = cfg.replace(
            sim=SimConfig(n_agents=3, n_scenarios=4, fused_slot=True)
        )
        pf = build_portfolio(["double_auction"], 4)
        with pytest.raises(ValueError, match="auction mechanism"):
            make_regime_episode_fn(
                cfg_fused, policy, ratings, pf.scenario_params,
                arrays_s=arrays, specs=pf.specs,
            )


# -- per-regime eval + promotion gate -----------------------------------------


@pytest.fixture(scope="module")
def crafted_regime_bundles(tmp_path_factory):
    from p2pmicrogrid_tpu.regimes.bench import make_regime_crafted_bundle

    root = tmp_path_factory.mktemp("regime-bundles")
    cfg = default_config(
        sim=SimConfig(n_agents=3),
        train=TrainConfig(implementation="tabular"),
    )
    inc = make_regime_crafted_bundle(cfg, "thermostat", str(root / "inc"))
    cand = make_regime_crafted_bundle(cfg, "siesta", str(root / "cand"))
    return cfg, inc, cand


class TestRegimeEval:
    def test_eval_table_fields_and_events(self, world, tmp_path):
        cfg, ratings, _, policy = world
        from p2pmicrogrid_tpu.regimes import evaluate_regimes
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        db = str(tmp_path / "regimes.db")
        tel = Telemetry(
            run_id="regime-eval-test",
            sinks=[SqliteSink(db)],
            manifest={"run_id": "regime-eval-test", "created": 0.0,
                      "config_hash": "cfgRE", "git_rev": "t",
                      "setting": "s", "backend": "cpu"},
        )
        rows = evaluate_regimes(
            cfg, policy, ps, ratings, ["winter", "ev_evening"],
            s_per_regime=2, telemetry=tel, held_out=True,
        )
        tel.close()
        assert [r["regime"] for r in rows] == ["winter", "ev_evening"]
        for r in rows:
            assert r["held_out"] is True
            assert np.isfinite(r["cost_eur"])
            assert "comfort_violations" in r and "trade_wh" in r
        assert rows[1]["ev_charged_wh"] > 0.0

        from p2pmicrogrid_tpu.data.results import ResultsStore

        store = ResultsStore(db)
        view = store.query_regime_view()
        store.close()
        assert {v["regime"] for v in view} == {"winter", "ev_evening"}
        row = {v["regime"]: v for v in view}["ev_evening"]
        assert row["config_hash"] == "cfgRE"
        assert row["n_held_out_evals"] == 1
        assert row["mean_ev_charged_wh"] > 0.0

    def test_gate_blocks_held_out_regime_regression(
        self, crafted_regime_bundles
    ):
        """The acceptance case: the siesta candidate BEATS the incumbent
        thermostat on mean held-out cost (the plain gate passes it) but
        back-loads heating into the evening spike — the regime-aware gate
        must block it, naming the regressed regime."""
        from p2pmicrogrid_tpu.serve.promotion import (
            GateBudgets,
            run_promotion_gate,
        )

        cfg, inc, cand = crafted_regime_bundles
        service = lambda batch, padded: 1e-3
        plain = run_promotion_gate(
            cfg, cand, inc, budgets=GateBudgets(),
            service_time_fn=service,
        )
        assert plain.passed, plain.reasons
        assert plain.candidate_cost < plain.incumbent_cost
        gated = run_promotion_gate(
            cfg, cand, inc, budgets=GateBudgets(),
            service_time_fn=service,
            regime_specs=["dr_spike", "islanding_noon"],
            regime_s_per_regime=2,
        )
        assert not gated.passed
        assert any("dr_spike" in r for r in gated.reasons)
        assert gated.candidate_regime_costs["dr_spike"] > (
            gated.incumbent_regime_costs["dr_spike"]
        )
        # The verdict's warehouse fields carry the per-regime evidence.
        fields = gated.to_fields()
        assert set(fields["candidate_regime_costs"]) == {
            "dr_spike", "islanding_noon"
        }

    def test_gate_regime_rule_pass_and_injection(
        self, crafted_regime_bundles
    ):
        """Injected per-regime evals: no regression -> pass; regression
        within the tolerance -> pass; the incumbent_regime_eval reuse
        path works (the harness gates many candidates against one)."""
        from p2pmicrogrid_tpu.serve.promotion import (
            GateBudgets,
            run_promotion_gate,
        )

        cfg, inc, cand = crafted_regime_bundles
        service = lambda batch, padded: 1e-3
        evals = {
            cand: {"cold_snap": 9.0, "dr_spike": 5.0},
            inc: {"cold_snap": 10.0, "dr_spike": 4.9},
        }
        fake = lambda d: dict(evals[d])
        ok = run_promotion_gate(
            cfg, cand, inc,
            budgets=GateBudgets(max_regime_regression=0.05),
            service_time_fn=service, regime_eval_fn=fake,
            incumbent_regime_eval=evals[inc],
        )
        # dr_spike 5.0 vs 4.9: within the 5% scale-free tolerance.
        assert ok.passed, ok.reasons
        strict = run_promotion_gate(
            cfg, cand, inc, budgets=GateBudgets(),
            service_time_fn=service, regime_eval_fn=fake,
        )
        assert not strict.passed
        assert any("dr_spike" in r for r in strict.reasons)


# -- CLI + schema --------------------------------------------------------------


class TestRegimeCli:
    def test_telemetry_query_regimes_view_and_watch_refusal(self, tmp_path, capsys):
        from p2pmicrogrid_tpu.cli import main
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        db = str(tmp_path / "w.db")
        tel = Telemetry(
            run_id="r1", sinks=[SqliteSink(db)],
            manifest={"run_id": "r1", "created": 0.0,
                      "config_hash": "cfgX", "git_rev": "t",
                      "setting": "s", "backend": "cpu"},
        )
        tel.event(
            "regime_eval", regime="winter", held_out=True, cost_eur=3.5,
            reward=-2.0, comfort_violations=1.0, trade_wh=10.0,
            grid_wh=100.0, curtailed_wh=0.0, ev_charged_wh=0.0,
            ev_missed_wh=0.0, n_scenarios=2,
        )
        tel.close()
        rc = main(["telemetry-query", "--results-db", db, "--regimes"])
        out = capsys.readouterr().out.strip().splitlines()
        assert rc == 0
        rows = [json.loads(l) for l in out]
        assert rows and rows[0]["regime"] == "winter"
        assert rows[0]["config_hash"] == "cfgX"
        assert rows[0]["mean_cost_eur"] == pytest.approx(3.5)

        rc = main([
            "telemetry-query", "--results-db", db, "--regimes", "--watch",
        ])
        assert rc == 2
        assert "--regimes" in capsys.readouterr().err


class TestRegimeSchema:
    GOOD_EVAL = {
        "metric": "regime_eval", "value": 3.2, "unit": "eur/scenario-day",
        "vs_baseline": 1.0, "regime": "winter", "held_out": True,
        "cost_eur": 3.2,
    }
    GOOD_GATE = {
        "metric": "regime_gate_case", "value": 1.0, "unit": "blocked",
        "vs_baseline": 1.0, "blocked": True, "mean_improved": True,
        "regressed_regime": "dr_spike",
    }
    GOOD_HEAD = {
        "metric": "regime_generalization_tabular_2train_2held_out",
        "value": 4.0, "unit": "eur/scenario-day", "vs_baseline": 1.0,
        "held_out": True, "single_compile": True,
        "train_cost_eur": 3.0, "held_out_cost_eur": 4.0,
        "generalization_gap": 1.0,
        "train_regimes": ["baseline", "winter"],
        "held_out_regimes": ["dr_spike", "cold_snap"],
        "per_regime_cost": {"baseline": 2.9, "dr_spike": 4.5},
    }

    def _write(self, path, rows):
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    def test_good_capture_passes(self, tmp_path):
        p = str(tmp_path / "REGIME_t.jsonl")
        self._write(p, [self.GOOD_EVAL, self.GOOD_GATE, self.GOOD_HEAD])
        problems = []
        schema.check_regime_jsonl(p, problems)
        assert problems == []

    @pytest.mark.parametrize(
        "mutate, needle",
        [
            (lambda rows: rows[0].pop("cost_eur"), "cost_eur"),
            (lambda rows: rows[0].pop("regime"), "regime"),
            (lambda rows: rows[1].pop("blocked"), "blocked"),
            (lambda rows: rows[2].pop("per_regime_cost"), "per_regime_cost"),
            (
                lambda rows: rows[2].__setitem__("held_out_regimes", []),
                "held_out_regimes",
            ),
            (
                lambda rows: rows[2].__setitem__("single_compile", "yes"),
                "single_compile",
            ),
            (lambda rows: rows.reverse(), "last row"),
            (lambda rows: rows.pop(2), "headline"),
        ],
    )
    def test_bad_captures_flagged(self, tmp_path, mutate, needle):
        rows = [
            json.loads(json.dumps(r))
            for r in (self.GOOD_EVAL, self.GOOD_GATE, self.GOOD_HEAD)
        ]
        mutate(rows)
        p = str(tmp_path / "REGIME_bad.jsonl")
        self._write(p, rows)
        problems = []
        schema.check_regime_jsonl(p, problems)
        assert problems, f"expected a problem mentioning {needle!r}"
        assert any(needle in pr for pr in problems), problems

    def test_check_all_sweeps_regime_captures(self, tmp_path):
        art = tmp_path / "artifacts"
        art.mkdir()
        self._write(
            str(art / "REGIME_x.jsonl"),
            [self.GOOD_EVAL, self.GOOD_GATE],  # headline missing
        )
        problems = schema.check_all(str(tmp_path))
        assert any("regime_generalization headline" in p for p in problems)

    def test_committed_capture_validates(self):
        path = os.path.join(REPO_ROOT, "artifacts", "REGIME_r13.jsonl")
        assert os.path.exists(path), "committed REGIME_r13.jsonl missing"
        problems = []
        schema.check_regime_jsonl(path, problems)
        assert problems == []
        rows = [json.loads(l) for l in open(path) if l.strip()]
        head = rows[-1]
        assert head["single_compile"] is True
        assert head["gate_blocked_regime_regression"] is True
        gate = [r for r in rows if r["metric"] == "regime_gate_case"][0]
        assert gate["blocked"] and gate["mean_improved"]
        assert gate["passed_without_regime_gate"]


class TestSpecs:
    def test_library_and_resolve(self):
        specs = resolve_specs(["winter", RegimeSpec(name="custom")])
        assert specs[0].temp_offset_c < 0
        assert specs[1].name == "custom"
        with pytest.raises(ValueError, match="unknown regime"):
            resolve_specs(["no_such_regime"])

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="mechanism"):
            RegimeSpec(mechanism="vickrey")
        with pytest.raises(ValueError, match="EV window"):
            RegimeSpec(ev_arrival_slot=90, ev_deadline_slot=80)

    def test_fused_unstageable_features(self):
        assert REGIME_LIBRARY["baseline"].fused_unstageable_features() == []
        feats = REGIME_LIBRARY["ev_evening"].fused_unstageable_features()
        assert feats == ["EV load"]
        assert REGIME_LIBRARY["baseline"].is_baseline
        assert not REGIME_LIBRARY["winter"].is_baseline
