"""Resilient serve fleet: router, failover, fault harness (ISSUE 6).

Tier-1 acceptance for the fleet tier: consistent-hash routing moves only
a lost replica's households, a replica kill mid-traffic loses zero
admitted requests (households re-pin to healthy replicas, responses stay
bit-identical to direct engine calls), health probes eject and re-admit,
retry-budget exhaustion degrades to a 503 + Retry-After shed, a
fleet-wide two-phase swap drops nothing, and the seed-driven fault
harness replays exactly. Fast and JAX_PLATFORMS=cpu-safe by design.
"""

import asyncio
import collections
import http.client
import importlib.util
import json
import os
import random
import threading
import time

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.serve import (
    AdmissionConfig,
    ConsistentHashRing,
    FaultEvent,
    FaultInjector,
    FaultPlan,
    FleetRouter,
    FleetSwapError,
    GatewayServer,
    LocalFleet,
    RetryBudget,
    RetryPolicy,
    build_gateway,
    export_policy_bundle,
    kill_restart_plan,
    run_fleet_loadgen,
    run_network_loadgen,
    serve_bench_fleet,
)
from p2pmicrogrid_tpu.train import init_policy_state

A = 3  # community size for all fleet tests

# Admission effectively off for serving-semantics tests: shedding has its
# own dedicated tests with forced budgets, and a loaded CI machine must
# not trip the default wait budget mid-assertion.
_OPEN_ADMISSION = AdmissionConfig(
    max_queue_depth=100_000, wait_budget_ms=100_000.0
)


def _make_bundle(tmp_path, seed, name):
    cfg = default_config(
        sim=SimConfig(n_agents=A),
        train=TrainConfig(implementation="tabular", seed=seed),
    )
    ps = init_policy_state(cfg, jax.random.PRNGKey(seed))
    ps = ps._replace(
        q_table=jax.random.normal(
            jax.random.PRNGKey(seed + 1), ps.q_table.shape
        )
    )
    return export_policy_bundle(cfg, ps, str(tmp_path / name))


def _obs(n, seed=0):
    rng = np.random.default_rng(seed)
    obs = np.empty((n, A, 4), dtype=np.float32)
    obs[..., 0] = rng.uniform(0, 1, (n, A))
    obs[..., 1:] = rng.uniform(-1, 1, (n, A, 3))
    return obs


def _act(router, household, obs_row, **kw):
    return asyncio.run(router.act(household, obs_row, **kw))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_artifacts_schema",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "check_artifacts_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bundles(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fleet-bundles")
    return _make_bundle(tmp, 0, "b1"), _make_bundle(tmp, 1, "b2")


class TestHashRing:
    def test_deterministic_and_balanced(self):
        ring = ConsistentHashRing(vnodes=64)
        for r in ("replica-0", "replica-1", "replica-2"):
            ring.add(r)
        keys = [f"house-{i}" for i in range(1500)]
        routed = {k: ring.lookup(k) for k in keys}
        # Deterministic: a second ring built the same way agrees exactly.
        ring2 = ConsistentHashRing(vnodes=64)
        for r in ("replica-0", "replica-1", "replica-2"):
            ring2.add(r)
        assert all(ring2.lookup(k) == routed[k] for k in keys)
        # Balanced within consistent-hashing tolerance.
        counts = collections.Counter(routed.values())
        assert set(counts) == {"replica-0", "replica-1", "replica-2"}
        assert min(counts.values()) > 1500 / 3 * 0.6

    def test_remove_moves_only_owned_keys(self):
        """THE consistent-hashing property: losing a replica re-routes
        only ITS households (to their next-clockwise survivor)."""
        ring = ConsistentHashRing(vnodes=64)
        for r in ("replica-0", "replica-1", "replica-2"):
            ring.add(r)
        keys = [f"house-{i}" for i in range(1500)]
        before = {k: ring.lookup(k) for k in keys}
        ring.remove("replica-1")
        moved = [k for k in keys if ring.lookup(k) != before[k]]
        assert moved  # replica-1 owned some keys
        assert all(before[k] == "replica-1" for k in moved)
        # Re-adding restores the original assignment exactly.
        ring.add("replica-1")
        assert all(ring.lookup(k) == before[k] for k in keys)

    def test_predicate_walks_clockwise(self):
        ring = ConsistentHashRing(vnodes=8)
        ring.add("a")
        ring.add("b")
        assert ring.lookup("key", accept=lambda r: r == "b") == "b"
        assert ring.lookup("key", accept=lambda r: False) is None
        with pytest.raises(ValueError):
            ring.add("a")
        with pytest.raises(KeyError):
            ring.remove("zzz")


class TestRetryPrimitives:
    def test_backoff_capped_jittered_honors_retry_after(self):
        policy = RetryPolicy(
            backoff_base_s=0.1, backoff_cap_s=0.5, jitter=0.5
        )
        rng = random.Random(0)
        for attempt in range(8):
            d = policy.backoff_s(attempt, rng)
            cap = min(0.5, 0.1 * 2 ** attempt)
            assert cap * 0.5 <= d <= cap  # jittered within [cap/2, cap]
        # Retry-After dominates when larger than the computed backoff.
        assert policy.backoff_s(0, rng, retry_after_s=2.0) == 2.0
        # ... but is ignored when the policy says not to honor it.
        no_honor = RetryPolicy(
            backoff_base_s=0.1, jitter=0.0, honor_retry_after=False
        )
        assert no_honor.backoff_s(0, rng, retry_after_s=2.0) == 0.1

    def test_budget_tokens(self):
        budget = RetryBudget(ratio=0.5, min_tokens=1.0, cap=2.0)
        assert budget.try_spend()          # the starting balance
        assert not budget.try_spend()      # drained
        for _ in range(4):                 # deposits at ratio per attempt
            budget.on_attempt()
        assert budget.tokens == 2.0        # capped
        assert budget.try_spend() and budget.try_spend()
        assert not budget.try_spend()
        assert budget.spent == 3 and budget.denied == 2


class TestFaultPlan:
    def test_same_seed_same_faults(self):
        plan = FaultPlan(
            seed=7,
            events=[
                FaultEvent(kind="error", rate=0.25),
                FaultEvent(kind="corrupt", rate=0.1),
            ],
        )
        a = FaultInjector(plan, "replica-0")
        b = FaultInjector(plan, "replica-0")
        seq_a = [d.kind if d else None for d in (a.decide() for _ in range(300))]
        seq_b = [d.kind if d else None for d in (b.decide() for _ in range(300))]
        assert seq_a == seq_b
        assert "error" in seq_a and "corrupt" in seq_a  # both events fired
        # A different seed draws a different sequence...
        c = FaultInjector(
            FaultPlan(seed=8, events=plan.events), "replica-0"
        )
        assert seq_a != [
            d.kind if d else None for d in (c.decide() for _ in range(300))
        ]
        # ... and so does a different replica id under the SAME seed.
        d_inj = FaultInjector(plan, "replica-1")
        assert seq_a != [
            d.kind if d else None
            for d in (d_inj.decide() for _ in range(300))
        ]

    def test_replica_and_scope_filters(self):
        plan = FaultPlan(
            seed=0,
            events=[
                FaultEvent(kind="error", replica="replica-1", rate=1.0),
                FaultEvent(
                    kind="stall", scope="health", rate=1.0, stall_s=0.5
                ),
            ],
        )
        other = FaultInjector(plan, "replica-0")
        assert other.decide(scope="act") is None  # error targets replica-1
        assert other.decide(scope="health").kind == "stall"
        target = FaultInjector(plan, "replica-1")
        assert target.decide(scope="act").kind == "error"

    def test_json_round_trip_and_validation(self):
        plan = kill_restart_plan(
            "replica-2", 0.25, 0.75, seed=3,
            extra_events=(FaultEvent(kind="drop", rate=0.05),),
        )
        back = FaultPlan.from_json(plan.to_json())
        assert back == plan
        assert [e.kind for e in back.lifecycle_events()] == [
            "kill", "restart"
        ]
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultEvent(kind="meteor")
        with pytest.raises(ValueError, match="rate"):
            FaultEvent(kind="error", rate=1.5)
        with pytest.raises(ValueError, match="name a replica"):
            FaultEvent(kind="kill")
        with pytest.raises(ValueError, match="restart_at_s"):
            kill_restart_plan("r", 1.0, 0.5)
        with pytest.raises(ValueError, match="fault_plan"):
            FaultPlan.from_json("{}")

    def test_act_coins_independent_of_health_probes(self):
        """Health probes fire on their own nondeterministic timer; they
        must not shift the act-scope fault sequence between otherwise
        identical runs (per-scope request counters)."""
        plan = FaultPlan(seed=9, events=[FaultEvent(kind="error", rate=0.4)])
        clean = FaultInjector(plan, "replica-0")
        want = [clean.decide("act") is not None for _ in range(120)]
        noisy = FaultInjector(plan, "replica-0")
        got = []
        for i in range(120):
            if i % 3 == 0:  # interleaved probes, arbitrary cadence
                noisy.decide("health")
            got.append(noisy.decide("act") is not None)
        assert got == want

    def test_request_coins_stable_under_lifecycle_edits(self):
        """Adding kill/restart events must not shift request-fault coins
        (the plan index, not the filtered position, feeds the hash)."""
        base = FaultPlan(seed=5, events=[FaultEvent(kind="error", rate=0.3)])
        edited = FaultPlan(
            seed=5,
            events=[FaultEvent(kind="error", rate=0.3),
                    FaultEvent(kind="kill", replica="r0", at_s=1.0)],
        )
        a = FaultInjector(base, "replica-0")
        b = FaultInjector(edited, "replica-0")
        assert [d is not None for d in (a.decide() for _ in range(100))] == [
            d is not None for d in (b.decide() for _ in range(100))
        ]


class TestGatewayFaultHooks:
    """Request-level fault injection through a single gateway."""

    def _gateway(self, bundles, plan):
        injector = FaultInjector(plan, "replica-0")
        gateway = build_gateway(
            [bundles[0]], max_batch=4, admission=_OPEN_ADMISSION,
            fault_injector=injector, replica_id="replica-0",
        )
        return gateway, injector

    def _post_act(self, host, port, obs_row, timeout=30):
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request(
                "POST", "/v1/act",
                body=json.dumps({"household": "h", "obs": obs_row.tolist()}),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            raw = resp.read()
            try:
                doc = json.loads(raw) if raw else {}
            except (json.JSONDecodeError, UnicodeDecodeError):
                doc = None
            return resp.status, doc, raw
        finally:
            conn.close()

    def test_injected_error_and_stats(self, bundles):
        plan = FaultPlan(seed=0, events=[FaultEvent(kind="error", rate=1.0)])
        gateway, injector = self._gateway(bundles, plan)
        with GatewayServer(gateway):
            status, doc, _ = self._post_act(
                gateway.host, gateway.port, _obs(1)[0]
            )
            assert status == 500 and "injected fault" in doc["error"]
            assert gateway.stats["faults_injected"] == 1
            assert injector.injected["error"] == 1

    def test_injected_corruption_is_detectable(self, bundles):
        plan = FaultPlan(
            seed=0, events=[FaultEvent(kind="corrupt", rate=1.0)]
        )
        gateway, _ = self._gateway(bundles, plan)
        with GatewayServer(gateway):
            status, doc, raw = self._post_act(
                gateway.host, gateway.port, _obs(1)[0]
            )
            # Framing intact (full body delivered), payload unparseable.
            assert status == 200 and doc is None and raw.startswith(b"\xff")

    def test_injected_stall_delays_response(self, bundles):
        plan = FaultPlan(
            seed=0,
            events=[FaultEvent(kind="stall", rate=1.0, stall_s=0.2)],
        )
        gateway, _ = self._gateway(bundles, plan)
        with GatewayServer(gateway):
            t0 = time.monotonic()
            status, _, _ = self._post_act(
                gateway.host, gateway.port, _obs(1)[0]
            )
            assert status == 200
            assert time.monotonic() - t0 >= 0.2

    def test_injected_drop_closes_without_response(self, bundles):
        plan = FaultPlan(seed=0, events=[FaultEvent(kind="drop", rate=1.0)])
        gateway, _ = self._gateway(bundles, plan)
        with GatewayServer(gateway):
            with pytest.raises((http.client.HTTPException, OSError)):
                self._post_act(gateway.host, gateway.port, _obs(1)[0])

    def test_health_scope_only_hits_health_endpoints(self, bundles):
        plan = FaultPlan(
            seed=0,
            events=[FaultEvent(kind="error", scope="health", rate=1.0)],
        )
        gateway, _ = self._gateway(bundles, plan)
        with GatewayServer(gateway):
            conn = http.client.HTTPConnection(
                gateway.host, gateway.port, timeout=30
            )
            try:
                conn.request("GET", "/readyz")
                assert conn.getresponse().status == 500
            finally:
                conn.close()
            status, _, _ = self._post_act(
                gateway.host, gateway.port, _obs(1)[0]
            )
            assert status == 200  # act traffic untouched


class TestGatewayHardening:
    def test_readyz_reports_config_hash_and_replica_id(self, bundles):
        gateway = build_gateway(
            [bundles[0]], max_batch=4, replica_id="replica-7"
        )
        with GatewayServer(gateway):
            conn = http.client.HTTPConnection(
                gateway.host, gateway.port, timeout=30
            )
            try:
                conn.request("GET", "/readyz")
                resp = conn.getresponse()
                doc = json.loads(resp.read())
            finally:
                conn.close()
            assert resp.status == 200
            assert doc["config_hash"] == gateway.registry.default_hash
            assert doc["replica_id"] == "replica-7"
            # /stats carries the replica identity too.
            assert gateway.stats_snapshot()["replica_id"] == "replica-7"

    def test_stop_idempotent_repeated_and_concurrent(self, bundles):
        gateway = build_gateway([bundles[0]], max_batch=4)
        server = GatewayServer(gateway)
        server.start()
        errors = []

        def stopper():
            try:
                server.stop()
            except Exception as err:  # noqa: BLE001
                errors.append(err)

        threads = [threading.Thread(target=stopper) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors == []
        server.stop()  # repeated call after full teardown is a no-op
        # The gateway coroutine path is idempotent too.
        asyncio.run(gateway.stop())
        asyncio.run(gateway.stop())
        # Bundles were closed exactly once and stayed closed.
        for h in gateway.registry.hashes:
            assert gateway.registry.get(h).queue._closed


@pytest.fixture
def fleet3(bundles):
    """A running 3-replica fleet over one bundle + a router with fast
    health thresholds (CI-friendly: ejection after 2 failures, re-admit
    after 1 success)."""
    fleet = LocalFleet(
        [bundles[0]], n_replicas=3, max_batch=4,
        admission=_OPEN_ADMISSION,
    )
    fleet.start()
    router = FleetRouter(
        fleet.replicas,
        retry=RetryPolicy(max_attempts=5, deadline_s=30.0),
        fail_threshold=2,
        ok_threshold=1,
    )
    try:
        yield fleet, router
    finally:
        fleet.stop_all()


class TestFleetFailover:
    def test_kill_mid_traffic_zero_lost_repinned_bit_exact(self, fleet3):
        """ISSUE 6 acceptance core: a replica kill loses zero admitted
        requests, its households re-pin to healthy replicas, and every
        served response stays bit-identical to a direct engine call."""
        fleet, router = fleet3
        engine = fleet.reference_engine()
        obs = _obs(24, seed=3)
        homes = [f"house-{i}" for i in range(8)]
        # Wave 1: map households to their home replicas.
        first = {}
        for i, h in enumerate(homes):
            r = _act(router, h, obs[i])
            assert r.status == 200
            first[h] = r.replica_id
        victim = first[homes[0]]
        affected = [h for h, rid in first.items() if rid == victim]
        fleet.kill(victim)
        # Wave 2 mid-outage: every request still answers 200.
        results = {}
        for i, h in enumerate(homes):
            r = _act(router, h, obs[8 + i])
            assert r.status == 200, (h, r.status, r.error)
            results[h] = r
        # Affected households failed over away from the victim and are
        # pinned to the replica that actually served them.
        pins = router.pinned_households()
        for h in affected:
            assert results[h].replica_id != victim
            assert pins.get(h) == results[h].replica_id
            assert router.is_healthy(results[h].replica_id)
        # Unaffected households never moved (consistent-hash locality).
        for h in homes:
            if h not in affected:
                assert results[h].replica_id == first[h]
        assert router.counters["failovers"] >= 1
        # Bit-exactness across the kill: responses == direct engine.act.
        got = np.asarray(
            [results[h].actions for h in homes], dtype=np.float32
        )
        want = engine.act(obs[8:8 + len(homes)])
        np.testing.assert_array_equal(got, want)
        # Restart: the replica rejoins on its original port and serves.
        fleet.restart(victim)
        router.probe_once()
        assert router.is_healthy(victim)
        r = _act(router, "brand-new-house", _obs(1, seed=9)[0])
        assert r.status == 200

    def test_probe_ejects_and_readmits(self, fleet3):
        fleet, router = fleet3
        victim = router.replica_ids[1]
        fleet.kill(victim)
        assert router.is_healthy(victim)  # not yet observed
        router.probe_once()
        assert router.is_healthy(victim)  # 1 of fail_threshold=2
        router.probe_once()
        assert not router.is_healthy(victim)  # ejected
        assert router.counters["ejections"] == 1
        assert set(router.healthy_ids()) == (
            set(router.replica_ids) - {victim}
        )
        fleet.restart(victim)
        router.probe_once()  # ok_threshold=1 -> re-admitted
        assert router.is_healthy(victim)
        assert router.counters["readmissions"] == 1

    def test_all_replicas_down_sheds_immediately(self, fleet3):
        fleet, router = fleet3
        for rid in router.replica_ids:
            fleet.kill(rid)
        for _ in range(2):
            router.probe_once()
        assert router.healthy_ids() == []
        t0 = time.monotonic()
        r = _act(router, "h", _obs(1)[0])
        assert r.status == 503 and r.shed
        assert r.retry_after_s == router.shed_retry_after_s
        # Shed, not queued: the answer is immediate.
        assert time.monotonic() - t0 < 5.0
        assert router.counters["shed"] >= 1

    def test_retry_budget_exhaustion_degrades_503(self, bundles):
        """Every replica 500s; a drained budget must stop the retry storm
        and shed with Retry-After instead."""
        plan = FaultPlan(
            seed=0, events=[FaultEvent(kind="error", rate=1.0)]
        )
        fleet = LocalFleet(
            [bundles[0]], n_replicas=2, max_batch=4,
            admission=_OPEN_ADMISSION, fault_plan=plan,
        )
        fleet.start()
        router = FleetRouter(
            fleet.replicas,
            retry=RetryPolicy(
                max_attempts=10, deadline_s=30.0,
                backoff_base_s=0.001, backoff_cap_s=0.002,
            ),
            budget=RetryBudget(ratio=0.0, min_tokens=2.0),
            fail_threshold=100,  # keep replicas routable: isolate budget
            ok_threshold=1,
        )
        try:
            r = _act(router, "h", _obs(1)[0])
            assert r.status == 503 and r.shed and r.gave_up
            assert "retry budget" in r.error
            assert r.retry_after_s == router.shed_retry_after_s
            assert router.counters["budget_denied"] == 1
            # The two budget tokens were the only retries spent.
            assert router.budget.spent == 2
        finally:
            fleet.stop_all()

    def test_retries_recover_from_injected_errors(self, bundles):
        """Deterministic 50% 500-rate on one replica of two: with retry +
        failover every request must still answer 200, bit-exact."""
        plan = FaultPlan(
            seed=11,
            events=[
                FaultEvent(kind="error", replica="replica-0", rate=0.5)
            ],
        )
        fleet = LocalFleet(
            [bundles[0]], n_replicas=2, max_batch=4,
            admission=_OPEN_ADMISSION, fault_plan=plan,
        )
        fleet.start()
        router = FleetRouter(
            fleet.replicas,
            retry=RetryPolicy(
                max_attempts=6, deadline_s=30.0,
                backoff_base_s=0.001, backoff_cap_s=0.01,
            ),
            fail_threshold=1000,  # never eject: exercise per-request paths
        )
        engine = fleet.reference_engine()
        obs = _obs(12, seed=4)
        try:
            actions = []
            for i in range(12):
                r = _act(router, f"house-{i}", obs[i])
                assert r.status == 200, (i, r.status, r.error)
                actions.append(r.actions)
            assert router.counters["retries"] >= 1
            np.testing.assert_array_equal(
                np.asarray(actions, dtype=np.float32), engine.act(obs)
            )
        finally:
            fleet.stop_all()


class TestRouterAccounting:
    def test_429_retry_is_not_a_failover(self, bundles):
        """Anonymous 429 retries round-robin to another replica — that is
        load balancing over a SATURATED-but-healthy fleet, and must not
        count into the failover SLO."""
        plans = AdmissionConfig(
            wait_budget_ms=5.0, min_wait_samples=8,
            retry_after_s=0.3, wait_window_s=0.15,
        )
        fleet = LocalFleet(
            [bundles[0]], n_replicas=2, max_batch=4, admission=plans,
        )
        fleet.start()
        now = time.monotonic()
        for rid in ("replica-0", "replica-1"):
            q = fleet.entry(rid)["registry"]
            bundle = q.get(q.default_hash)
            for _ in range(16):
                bundle.queue.recent_wait_ms.append((now, 100.0))
        router = FleetRouter(
            fleet.replicas,
            retry=RetryPolicy(max_attempts=5, deadline_s=20.0),
        )
        try:
            r = _act(router, None, _obs(1)[0])  # anonymous: round-robins
            assert r.status == 200 and r.retries >= 1
            assert router.counters["retries"] >= 1
            assert router.counters["failovers"] == 0
            assert r.failovers == 0
        finally:
            fleet.stop_all()

    def test_injector_anchoring_is_harness_owned(self, bundles):
        """Gateway start must NOT activate the injector: the fleet bench
        anchors every replica's fault windows at the loadgen start, and a
        first-wins activate at server start would skew them by warmup."""
        plan = FaultPlan(
            seed=0, events=[FaultEvent(kind="error", rate=1.0)]
        )
        fleet = LocalFleet(
            [bundles[0]], n_replicas=2, max_batch=4,
            admission=_OPEN_ADMISSION, fault_plan=plan,
        )
        fleet.start()
        try:
            injectors = [
                fleet.entry(rid)["injector"] for rid in
                ("replica-0", "replica-1")
            ]
            assert all(i._t0 is None for i in injectors)
            t0 = time.monotonic()
            fleet.activate_faults(t0)
            assert all(i._t0 == t0 for i in injectors)
        finally:
            fleet.stop_all()


class TestFleetSwap:
    def test_two_phase_swap_zero_drops(self, bundles):
        """Fleet-wide hot-swap under live traffic: zero failed requests,
        every replica verified on the new config_hash via /readyz."""
        fleet = LocalFleet(
            list(bundles), n_replicas=2, max_batch=4,
            admission=_OPEN_ADMISSION,
        )
        fleet.start()
        router = FleetRouter(fleet.replicas, retry=RetryPolicy())
        try:
            entry = fleet.entry("replica-0")
            h1 = entry["registry"].default_hash
            h2 = [h for h in entry["registry"].hashes if h != h1][0]
            obs = _obs(1)[0]
            results = []

            def traffic():
                arrivals = np.arange(40) * 0.005
                results.append(
                    run_fleet_loadgen(
                        router, np.stack([obs] * 40), arrivals,
                        [f"house-{i}" for i in range(10)],
                    )
                )

            t = threading.Thread(target=traffic)
            t.start()
            time.sleep(0.05)  # swap lands mid-wave
            out = asyncio.run(router.swap_fleet(h2))
            t.join()
            assert out["config_hash"] == h2
            assert sorted(out["replicas"]) == sorted(router.replica_ids)
            result = results[0]
            # Zero drops through the swap, both configs (and only they)
            # served.
            assert result.n_ok == result.n_requests
            assert set(result.config_hashes) <= {h1, h2}
            # Every replica reports the new default on /readyz.
            for rid in router.replica_ids:
                rep = router.replica(rid)
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=30
                )
                try:
                    conn.request("GET", "/readyz")
                    doc = json.loads(conn.getresponse().read())
                finally:
                    conn.close()
                assert doc["config_hash"] == h2
            # Fleet flip recorded; router affinity pins reset.
            assert router.fleet_config_hash == h2
            assert router.pinned_count == 0
            # New traffic serves the new default everywhere.
            r = _act(router, "post-swap-house", obs)
            assert r.status == 200 and r.config_hash == h2
        finally:
            fleet.stop_all()

    def test_stale_replica_realigned_not_readmitted(self, bundles):
        """A replica that missed the fleet swap (killed around it) must
        not be re-admitted serving the OLD default: the probe sees the
        /readyz config_hash mismatch, re-pushes the swap, and only then
        re-admits — no silent half-swapped fleet."""
        fleet = LocalFleet(
            list(bundles), n_replicas=2, max_batch=4,
            admission=_OPEN_ADMISSION,
        )
        fleet.start()
        router = FleetRouter(
            fleet.replicas, fail_threshold=2, ok_threshold=1
        )
        try:
            entry = fleet.entry("replica-0")
            h1 = entry["registry"].default_hash
            h2 = [h for h in entry["registry"].hashes if h != h1][0]
            fleet.kill("replica-1")
            for _ in range(2):
                router.probe_once()
            assert not router.is_healthy("replica-1")
            asyncio.run(router.swap_fleet(h2))  # swaps replica-0 only
            fleet.restart("replica-1")  # warm registry: still defaults h1
            assert fleet.entry("replica-1")["registry"].default_hash == h1
            # First probe: mismatch detected, swap re-pushed, NOT ready.
            assert router.probe_once()["replica-1"] is False
            assert not router.is_healthy("replica-1")
            assert router.counters["swap_aligns"] == 1
            assert fleet.entry("replica-1")["registry"].default_hash == h2
            # Second probe verifies the aligned hash and re-admits.
            assert router.probe_once()["replica-1"] is True
            assert router.is_healthy("replica-1")
        finally:
            fleet.stop_all()

    def test_swap_unknown_hash_rolls_back(self, bundles):
        fleet = LocalFleet(
            list(bundles), n_replicas=2, max_batch=4,
            admission=_OPEN_ADMISSION,
        )
        fleet.start()
        router = FleetRouter(fleet.replicas)
        try:
            entry = fleet.entry("replica-0")
            h1 = entry["registry"].default_hash
            with pytest.raises(FleetSwapError, match="push answered 404"):
                asyncio.run(router.swap_fleet("deadbeef0000"))
            # Nothing moved: every replica still serves the old default.
            for rid in router.replica_ids:
                reg = fleet.entry(rid)["registry"]
                assert reg.default_hash == h1
            assert router.fleet_config_hash is None
        finally:
            fleet.stop_all()


class TestLoadgenRetry:
    def _shedding_gateway(self, bundles, wait_window_s):
        """A gateway whose p95-wait budget sheds until the stuffed wait
        samples age out of the window — deterministic saturation."""
        gateway = build_gateway(
            [bundles[0]], max_batch=4,
            admission=AdmissionConfig(
                wait_budget_ms=5.0, min_wait_samples=8,
                retry_after_s=0.3, wait_window_s=wait_window_s,
            ),
        )
        default = gateway.registry.get(gateway.registry.default_hash)
        now = time.monotonic()
        for _ in range(16):
            default.queue.recent_wait_ms.append((now, 100.0))
        return gateway

    def test_no_retry_preserves_shed_semantics(self, bundles):
        gateway = self._shedding_gateway(bundles, wait_window_s=0.15)
        with GatewayServer(gateway):
            result = run_network_loadgen(
                gateway.host, gateway.port, _obs(4), np.zeros(4),
                ["h0", "h1", "h2", "h3"],
            )
        assert result.n_shed == 4            # 429 stays terminal
        assert result.total_retries == 0
        assert result.retry_rate == 0.0 and result.n_gave_up == 0

    def test_retry_honors_retry_after_and_recovers(self, bundles):
        """With retry on, the 429 + Retry-After wave outlives the stuffed
        wait window, so every request succeeds on a later attempt."""
        gateway = self._shedding_gateway(bundles, wait_window_s=0.15)
        with GatewayServer(gateway):
            result = run_network_loadgen(
                gateway.host, gateway.port, _obs(4), np.zeros(4),
                ["h0", "h1", "h2", "h3"],
                retry=RetryPolicy(max_attempts=5, deadline_s=20.0),
            )
        assert result.n_ok == 4
        assert result.total_retries >= 4     # each request retried >= once
        assert result.retry_rate >= 1.0
        assert result.n_gave_up == 0
        # Latency includes the honored Retry-After backoff.
        assert float(result.latencies_s.min()) >= 0.3

    def test_retry_attempts_capped_by_deadline_under_stall(self, bundles):
        """A stalled replica must not let one attempt overrun the retry
        policy's per-request deadline by the full transport timeout."""
        plan = FaultPlan(
            seed=0,
            events=[FaultEvent(kind="stall", rate=1.0, stall_s=5.0)],
        )
        gateway = build_gateway(
            [bundles[0]], max_batch=4, admission=_OPEN_ADMISSION,
            fault_injector=FaultInjector(plan, "replica-0"),
        )
        with GatewayServer(gateway):
            t0 = time.monotonic()
            result = run_network_loadgen(
                gateway.host, gateway.port, _obs(1), np.zeros(1), ["h0"],
                timeout_s=30.0,
                retry=RetryPolicy(max_attempts=3, deadline_s=0.5),
            )
        # The deadline (0.5 s), not timeout_s (30 s), bounded the attempt.
        assert time.monotonic() - t0 < 3.0
        assert result.statuses[0] == -1
        assert float(result.latencies_s[0]) < 2.0

    def test_retry_gives_up_against_persistent_shed(self, bundles):
        gateway = self._shedding_gateway(bundles, wait_window_s=1e6)
        with GatewayServer(gateway):
            result = run_network_loadgen(
                gateway.host, gateway.port, _obs(2), np.zeros(2),
                ["h0", "h1"],
                retry=RetryPolicy(max_attempts=2, deadline_s=5.0),
            )
        assert result.n_shed == 2            # final outcome is still 429
        assert result.n_gave_up == 2
        assert result.total_retries == 2


class TestFleetBenchAndSchema:
    def test_chaos_bench_acceptance(self, bundles, tmp_path):
        """The ISSUE 6 acceptance run: kill/restart fault plan mid-bench;
        availability >= 99% of admitted requests, every household pinned
        to a healthy replica afterwards, responses bit-identical to the
        direct engine, and the capture passes the schema checker."""
        from p2pmicrogrid_tpu.data.results import ResultsStore
        from p2pmicrogrid_tpu.telemetry import (
            SqliteSink,
            Telemetry,
            run_manifest,
        )

        n_requests, rate = 160, 320.0
        duration = n_requests / rate
        plan = kill_restart_plan(
            "replica-1", kill_at_s=0.3 * duration,
            restart_at_s=0.6 * duration, seed=0,
        )
        db = str(tmp_path / "fleet.db")
        fleet = LocalFleet(
            [bundles[0]], n_replicas=3, max_batch=4,
            admission=_OPEN_ADMISSION, fault_plan=plan, results_db=db,
        )
        fleet.start()
        engine = fleet.reference_engine()
        tel = Telemetry(
            run_id="fleet-router-test",
            sinks=[SqliteSink(db)],
            manifest=run_manifest(
                extra={
                    "config_hash": engine.manifest.get("config_hash"),
                    "serve_role": "router",
                    "fleet_size": 3,
                }
            ),
        )
        router = FleetRouter(
            fleet.replicas,
            retry=RetryPolicy(max_attempts=6, deadline_s=30.0),
            fail_threshold=2, ok_threshold=1, telemetry=tel,
        )
        try:
            rows = serve_bench_fleet(
                router, n_agents=A, fleet=fleet, fault_plan=plan,
                reference_engine=engine, rate_hz=rate,
                n_requests=n_requests, n_households=12, seed=0,
                probe_interval_s=0.05,
            )
        finally:
            fleet.stop_all()
            tel.close()
        head = rows[-1]
        assert head["metric"] == "serve_bench_fleet"
        # The fault plan actually ran.
        assert head["chaos"]["kills"] == ["replica-1"]
        assert head["chaos"]["restarts"] == ["replica-1"]
        assert head["failover_count"] >= 1
        # Acceptance SLOs.
        assert head["availability"] >= 0.99
        assert head["bit_exact"] is True
        assert head["n_healthy"] == 3  # the fleet came back whole
        # Every pinned household points at a healthy replica.
        for h, rid in router.pinned_households().items():
            assert router.is_healthy(rid), (h, rid)
        # The capture passes the committed-artifact schema check.
        path = tmp_path / "FLEET_r00.jsonl"
        path.write_text(
            "".join(json.dumps(r) + "\n" for r in rows)
        )
        mod = _load_checker()
        problems: list = []
        mod.check_fleet_jsonl(str(path), problems)
        assert problems == []
        # Warehouse fleet view: replica bundle runs + the router run are
        # aggregated under the served config_hash with router counters.
        with ResultsStore(db) as store:
            view = store.query_fleet_view()
        assert len(view) == 1
        row = view[0]
        assert row["config_hash"] == engine.manifest.get("config_hash")
        assert row["n_runs"] == 4           # 3 replica bundles + router
        assert row["n_router_runs"] == 1
        assert row["n_serve_traces"] > 0
        assert row["router_failovers"] >= 1

    def test_fleet_jsonl_schema(self, tmp_path):
        mod = _load_checker()
        good = {
            "metric": "serve_bench_fleet", "value": 1.0, "unit": "ms",
            "vs_baseline": 1.0, "p50_ms": 0.5, "p95_ms": 0.9,
            "p99_ms": 1.0, "throughput_rps": 100.0, "availability": 0.999,
            "failover_count": 3, "retry_rate": 0.01, "shed_rate": 0.0,
        }
        path = tmp_path / "FLEET_r01.jsonl"
        path.write_text(json.dumps(good) + "\n")
        problems: list = []
        mod.check_fleet_jsonl(str(path), problems)
        assert problems == []
        # A missing SLO key is caught.
        bad = {k: v for k, v in good.items() if k != "availability"}
        path.write_text(json.dumps(bad) + "\n")
        problems = []
        mod.check_fleet_jsonl(str(path), problems)
        assert any("availability" in p for p in problems)
        # An out-of-range availability is caught.
        path.write_text(json.dumps(dict(good, availability=1.7)) + "\n")
        problems = []
        mod.check_fleet_jsonl(str(path), problems)
        assert any("outside" in p for p in problems)
        # check_all picks FLEET_*.jsonl up from artifacts/.
        artifacts = tmp_path / "artifacts"
        artifacts.mkdir()
        (artifacts / "FLEET_r02.jsonl").write_text(json.dumps(bad) + "\n")
        problems = mod.check_all(str(tmp_path))
        assert any("FLEET_r02" in p for p in problems)

    def test_serve_bench_fleet_cli_one_json_per_line(self, capfd):
        from p2pmicrogrid_tpu.cli import main

        rc = main([
            "serve-bench", "--fleet", "--chaos", "--agents", "2",
            "--implementation", "tabular", "--requests", "36",
            "--rate", "120", "--max-batch", "4", "--max-wait-ms", "1",
            "--households", "6", "--replicas", "2",
            "--max-queue-depth", "100000", "--wait-budget-ms", "100000",
        ])
        assert rc == 0
        out, err = capfd.readouterr()
        rows = [json.loads(l) for l in out.splitlines() if l.strip()]
        head = rows[-1]
        assert head["metric"] == "serve_bench_fleet"
        assert head["chaos"]["kills"] and head["chaos"]["restarts"]
        assert head["availability"] >= 0.99
        assert head["bit_exact"] is True
        assert "fleet of 2 replicas" in err
