"""Results-store and analysis-layer tests.

End-to-end oracle: train briefly, evaluate, persist to the relational store,
read back, and run the statistics/plots on real (tiny) data.
"""

import numpy as np
import jax
import pytest

from p2pmicrogrid_tpu.analysis import (
    analyse_community_output,
    community_summary,
    paired_cost_ttest,
    plot_cost_comparison,
    plot_cost_vs_community_size,
    plot_day_traces,
    plot_learning_curves,
    plot_pv_drop_comparison,
    plot_qtable_heatmap,
    plot_rounds_decisions,
    plot_scaling,
    statistical_tests,
)
from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.data import ResultsStore, save_eval_outputs, synthetic_traces
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.train import evaluate_community, init_policy_state, make_policy


@pytest.fixture(scope="module")
def eval_run():
    """One tiny eval run persisted under two fake settings."""
    cfg = default_config(
        sim=SimConfig(n_agents=2),
        train=TrainConfig(max_episodes=1, implementation="tabular"),
    )
    traces = synthetic_traces(n_days=3, start_day=8).normalized()
    rng = np.random.default_rng(42)
    ratings = make_ratings(cfg, rng)
    policy = make_policy(cfg)
    ps = init_policy_state(cfg, jax.random.PRNGKey(1))
    days, outputs, day_arrays = evaluate_community(
        cfg, policy, ps, traces, ratings, jax.random.PRNGKey(0), rng=rng
    )

    store = ResultsStore(":memory:")
    # Matched families: scale varies at fixed rounds-1; rounds vary at fixed
    # 2-agent size (the confounded-pool gating in statistical_tests requires
    # this, mirroring the reference's experiment design).
    for setting in (
        "2-multi-agent-com-rounds-1-hetero",
        "3-multi-agent-com-rounds-1-hetero",
        "2-multi-agent-com-rounds-3-hetero",
    ):
        save_eval_outputs(store, setting, "tabular", True, days, outputs, day_arrays)
        save_eval_outputs(store, setting, "tabular", False, days, outputs, day_arrays)
    for ep in range(0, 200, 50):
        store.log_training_progress(
            "2-multi-agent-com-rounds-1-hetero", "tabular", ep, -30000 + 100 * ep, 1.0
        )
    return cfg, store, days, outputs, day_arrays, ps


class TestResultsStore:
    def test_tables_exist_including_training_progress(self):
        store = ResultsStore(":memory:")
        rows = store.con.execute(
            "SELECT name FROM sqlite_master WHERE type='table'"
        ).fetchall()
        names = {r[0] for r in rows}
        assert {
            "environment",
            "load",
            "hyperparameters_single_day",
            "single_day_best_results",
            "validation_results",
            "test_results",
            "rounds_comparison",
            "training_progress",  # missing DDL in the reference, fixed here
        } <= names

    def test_roundtrip_test_results(self, eval_run):
        _, store, days, outputs, _, _ = eval_run
        df = store.get_test_results()
        n_days, T, A = np.asarray(outputs.cost).shape
        assert len(df) == 3 * n_days * T * A  # three settings
        # Costs survive the round trip.
        got = df[
            (df["setting"] == "2-multi-agent-com-rounds-1-hetero")
            & (df["day"] == int(days[0]))
            & (df["agent"] == 0)
        ].sort_values("time")["cost"].to_numpy()
        np.testing.assert_allclose(got, np.asarray(outputs.cost)[0, :, 0], rtol=1e-6)

    def test_rounds_decisions_roundtrip(self, eval_run):
        cfg, store, days, outputs, _, _ = eval_run
        df = store.get_rounds_decisions()
        assert set(df["round"].unique()) == set(range(cfg.sim.rounds + 1))

    def test_training_progress_roundtrip(self, eval_run):
        _, store, *_ = eval_run
        df = store.get_training_progress()
        assert len(df) == 4
        assert df["episode"].tolist() == [0, 50, 100, 150]


class TestReport:
    def test_summary_shapes_and_sanity(self, eval_run):
        cfg, _, _, outputs, day_arrays, _ = eval_run
        s = community_summary(outputs, day_arrays)
        A = cfg.sim.n_agents
        for k, v in s.items():
            assert v.shape == (A,), k
        assert (s["self_consumption_ratio"] <= 1.0 + 1e-6).all()
        assert (s["pv_energy_kwh"] > 0).all()

    def test_figures_render_and_save(self, eval_run, tmp_path):
        _, _, days, outputs, day_arrays, _ = eval_run
        summary, figs = analyse_community_output(
            days, outputs, day_arrays, save_dir=str(tmp_path)
        )
        assert {"costs", "self_consumption", "grid_load", "agent_0", "agent_1"} <= set(figs)
        assert (tmp_path / "grid_load.png").exists()


class TestStats:
    def test_paired_ttest(self, eval_run):
        _, store, *_ = eval_run
        df = store.get_test_results()
        r = paired_cost_ttest(
            df, "2-multi-agent-com-rounds-1-hetero", "3-multi-agent-com-rounds-1-hetero"
        )
        # Identical data -> zero diff, p is nan (0/0) or 1; mean_diff must be 0.
        assert r["mean_diff"] == pytest.approx(0.0)

    def test_battery_runs_on_store(self, eval_run):
        _, store, *_ = eval_run
        out = statistical_tests(store)
        assert "community_scale" in out
        assert "nr_rounds" in out
        assert 0 <= out["community_scale"]["p_anova"] <= 1 or np.isnan(
            out["community_scale"]["p_anova"]
        )


    def test_default_pairs_derive_thesis_comparisons(self, eval_run):
        """Round-3 fix: with no explicit pairs, the battery derives the
        reference's thesis comparisons (RL vs each baseline implementation,
        com vs no-com) from the table itself instead of running nothing."""
        from p2pmicrogrid_tpu.analysis.stats import default_comparison_pairs

        _, store, days, outputs, day_arrays, _ = eval_run
        extra = ResultsStore(":memory:")
        rl = "2-multi-agent-com-rounds-1-hetero"
        save_eval_outputs(extra, rl, "tabular", True, days, outputs, day_arrays)
        save_eval_outputs(
            extra, "2-multi-agent-no-com-hetero", "tabular", True,
            days, outputs, day_arrays,
        )
        for impl in ("rule-based", "semi-intelligent"):
            save_eval_outputs(
                extra, f"baseline-{rl}", impl, True, days, outputs, day_arrays
            )
        pairs = default_comparison_pairs(extra.get_test_results())
        assert (rl, f"baseline-{rl}[rule-based]") in pairs
        assert (rl, f"baseline-{rl}[semi-intelligent]") in pairs
        assert (rl, "2-multi-agent-no-com-hetero") in pairs
        out = statistical_tests(extra)
        assert any(k.startswith("ttest[") for k in out)
        # A second RL implementation under the SAME setting must not silence
        # the derivation: every RL label pairs against every twin.
        save_eval_outputs(extra, rl, "dqn", True, days, outputs, day_arrays)
        pairs2 = default_comparison_pairs(extra.get_test_results())
        assert (f"{rl}[tabular]", f"baseline-{rl}[rule-based]") in pairs2
        assert (f"{rl}[dqn]", "2-multi-agent-no-com-hetero") in pairs2


class TestPlots:
    def test_all_plots_render(self, eval_run):
        cfg, store, days, _, _, ps = eval_run
        assert plot_learning_curves(store.get_training_progress()) is not None
        assert plot_cost_comparison(store.get_test_results()) is not None
        assert (
            plot_day_traces(
                store.get_test_results(),
                "2-multi-agent-com-rounds-1-hetero",
                int(days[0]),
            )
            is not None
        )
        assert (
            plot_rounds_decisions(
                store.get_rounds_decisions(),
                "2-multi-agent-com-rounds-1-hetero",
                int(days[0]),
            )
            is not None
        )
        assert plot_qtable_heatmap(np.asarray(ps.q_table)[0]) is not None

    def test_scaling_figures(self):
        """Scaling figures from the timing JSON (data_analysis.py:775-845)."""
        timing = {
            "2-multi-agent-com-rounds-1-hetero": {"train": 10.0, "run": 1.0},
            "5-multi-agent-com-rounds-1-hetero": {"train": 22.0},
            "10-multi-agent-com-rounds-1-hetero": {"train": 41.0},
            "5-multi-agent-com-rounds-2-hetero": {"train": 33.0},
            "5-multi-agent-no-com-hetero": {"train": 9.0},  # skipped (no rounds)
        }
        fig = plot_scaling(timing)
        assert fig is not None
        ax_n, ax_r = fig.axes
        # One line per rounds value on the size panel; per size on the rounds.
        assert len(ax_n.lines) == 2 and len(ax_r.lines) == 3

    def test_cost_vs_community_size(self, eval_run):
        _, store, _, _, _, _ = eval_run
        assert plot_cost_vs_community_size(store.get_test_results()) is not None

    def test_pv_drop_comparison(self, eval_run):
        """PV-drop com-vs-no-com comparison (data_analysis.py:1099-1211)."""
        _, store, days, outputs, day_arrays, _ = eval_run
        from p2pmicrogrid_tpu.data import save_eval_outputs

        for s in ("2-agent-0-pv-drop-com", "2-agent-0-pv-drop-no-com"):
            save_eval_outputs(store, s, "tabular", True, days, outputs, day_arrays)
        fig = plot_pv_drop_comparison(
            store.get_test_results(),
            "2-agent-0-pv-drop-com",
            "2-agent-0-pv-drop-no-com",
        )
        assert fig is not None
        # Both settings plotted on each panel.
        assert all(len(ax.lines) == 2 for ax in fig.axes)


class TestTrainingHealth:
    def test_health_roundtrip_and_figure(self, tmp_path):
        """training_health rows round-trip and render as the two-panel
        cost/reward figure with basin/slide markers (plot_training_health)."""
        from p2pmicrogrid_tpu.analysis import plot_training_health

        store = ResultsStore(":memory:")
        rows = [
            (0, 3100.0, -1350.0, "healthy"),
            (10, 1500.0, -30.0, "slide"),
            (20, -400.0, -1400.0, "basin"),
            (30, 1200.0, -1.2, "healthy"),
        ]
        for ep, c, r, s in rows:
            store.log_training_health("s1", "ddpg", ep, c, r, s)
        df = store.get_training_health()
        assert len(df) == 4
        assert set(df.columns) >= {
            "setting", "implementation", "episode",
            "greedy_cost", "greedy_reward", "status",
        }
        assert (df.sort_values("episode")["status"].tolist()
                == [r[3] for r in rows])
        fig = plot_training_health(df)
        out = tmp_path / "training_health.png"
        fig.savefig(out)
        assert out.stat().st_size > 0
