"""Checkpoint round-trip tests (SURVEY.md section 4 oracle d)."""

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import DQNConfig, SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.train import init_policy_state
from p2pmicrogrid_tpu.train.checkpoint import (
    checkpoint_dir,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)


@pytest.mark.parametrize("impl", ["tabular", "dqn", "ddpg"])
def test_roundtrip(tmp_path, impl):
    cfg = default_config(
        sim=SimConfig(n_agents=2),
        train=TrainConfig(implementation=impl),
        dqn=DQNConfig(buffer_size=32),
    )
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    path = checkpoint_dir(str(tmp_path), cfg.setting, impl)
    save_checkpoint(path, ps, episode=7)

    template = init_policy_state(cfg, jax.random.PRNGKey(99))  # different init
    restored, episode = restore_checkpoint(path, template)
    assert episode == 7
    for a, b in zip(
        jax.tree_util.tree_leaves(ps), jax.tree_util.tree_leaves(restored)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_checkpoint_keeps_newest(tmp_path):
    cfg = default_config(sim=SimConfig(n_agents=2))
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    path = checkpoint_dir(str(tmp_path), cfg.setting, "tabular")
    save_checkpoint(path, ps, episode=10)
    save_checkpoint(path, ps, episode=20)
    assert latest_checkpoint(path).endswith("ep_20")


def test_missing_checkpoint_raises(tmp_path):
    cfg = default_config(sim=SimConfig(n_agents=2))
    template = init_policy_state(cfg, jax.random.PRNGKey(0))
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "nope"), template)
