"""Checkpoint round-trip tests (SURVEY.md section 4 oracle d)."""

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import DQNConfig, SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.train import init_policy_state
from p2pmicrogrid_tpu.train.checkpoint import (
    checkpoint_dir,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)


@pytest.mark.parametrize("impl", ["tabular", "dqn", "ddpg"])
def test_roundtrip(tmp_path, impl):
    cfg = default_config(
        sim=SimConfig(n_agents=2),
        train=TrainConfig(implementation=impl),
        dqn=DQNConfig(buffer_size=32),
    )
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    path = checkpoint_dir(str(tmp_path), cfg.setting, impl)
    save_checkpoint(path, ps, episode=7)

    template = init_policy_state(cfg, jax.random.PRNGKey(99))  # different init
    restored, episode = restore_checkpoint(path, template)
    assert episode == 7
    for a, b in zip(
        jax.tree_util.tree_leaves(ps), jax.tree_util.tree_leaves(restored)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_checkpoint_keeps_newest(tmp_path):
    cfg = default_config(sim=SimConfig(n_agents=2))
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    path = checkpoint_dir(str(tmp_path), cfg.setting, "tabular")
    save_checkpoint(path, ps, episode=10)
    save_checkpoint(path, ps, episode=20)
    assert latest_checkpoint(path).endswith("ep_20")


def test_missing_checkpoint_raises(tmp_path):
    cfg = default_config(sim=SimConfig(n_agents=2))
    template = init_policy_state(cfg, jax.random.PRNGKey(0))
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "nope"), template)


def _save_raw(path, payload):
    import orbax.checkpoint as ocp

    ocp.PyTreeCheckpointer().save(str(path), payload, force=True)


def test_older_subset_checkpoint_grafts_missing_fields(tmp_path):
    """A pre-0.2.0 DDPG checkpoint (no ``noise_scale``) restores with the
    missing leaf at its init default instead of refusing outright."""
    cfg = default_config(
        sim=SimConfig(n_agents=2), train=TrainConfig(implementation="ddpg")
    )
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    old_form = {f: getattr(ps, f) for f in ps._fields if f != "noise_scale"}
    old_form = jax.tree_util.tree_map(np.asarray, old_form)
    _save_raw(tmp_path / "ep_12", {"pol_state": old_form, "episode": 12})

    template = init_policy_state(cfg, jax.random.PRNGKey(99))
    with pytest.warns(UserWarning, match="noise_scale"):
        restored, episode = restore_checkpoint(str(tmp_path), template)
    assert episode == 12
    # Grafted leaf carries the template's init value...
    np.testing.assert_array_equal(
        np.asarray(restored.noise_scale), np.asarray(template.noise_scale)
    )
    # ...while every field the old file DID have restores from the file.
    np.testing.assert_array_equal(
        np.asarray(restored.ou_state), np.asarray(ps.ou_state)
    )


def test_newer_or_alien_checkpoint_still_raises(tmp_path):
    """Unknown fields mean a newer/different version: no silent graft."""
    cfg = default_config(
        sim=SimConfig(n_agents=2), train=TrainConfig(implementation="ddpg")
    )
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    alien = {
        f: jax.tree_util.tree_map(np.asarray, v) for f, v in zip(ps._fields, ps)
    }
    alien["from_the_future"] = np.ones(3)
    del alien["noise_scale"]  # force the item-restore mismatch
    _save_raw(tmp_path / "ep_3", {"pol_state": alien, "episode": 3})
    with pytest.raises(RuntimeError, match="from_the_future"):
        restore_checkpoint(str(tmp_path), init_policy_state(cfg, jax.random.PRNGKey(1)))


@pytest.mark.slow
def test_checkpoints_are_episode_exact_inside_fused_blocks(day_traces=None):
    """Round-3 VERDICT weak #7: with episodes_per_jit_block > 1, a
    save_episodes boundary inside a block used to get end-of-block state.
    Blocks are now chopped at the cadence, so the checkpoint at episode e
    equals the final state of an identically-seeded run with
    max_episodes = e + 1 (its first blocks chop identically)."""
    import dataclasses

    import jax
    import numpy as np

    from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
    from p2pmicrogrid_tpu.data import synthetic_traces
    from p2pmicrogrid_tpu.envs import make_ratings
    from p2pmicrogrid_tpu.train import (
        init_policy_state,
        make_policy,
        train_community,
    )

    cfg = default_config(
        sim=SimConfig(n_agents=2),
        train=TrainConfig(
            implementation="tabular", max_episodes=6,
            episodes_per_jit_block=4, save_episodes=3,
            min_episodes_criterion=2,
        ),
    )
    traces = synthetic_traces(n_days=1, seed=0, start_day=11).normalized()
    ratings = make_ratings(cfg, np.random.default_rng(0))
    policy = make_policy(cfg)
    ps0 = init_policy_state(cfg, jax.random.PRNGKey(1))

    saved = {}
    train_community(
        cfg, policy, ps0, traces, ratings, jax.random.PRNGKey(2),
        checkpoint_cb=lambda ep, ps: saved.__setitem__(
            ep, jax.tree_util.tree_map(np.asarray, ps)
        ),
    )
    assert 2 in saved  # cadence 3 -> checkpoint after episode index 2

    short = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, max_episodes=3)
    )
    res = train_community(
        short, policy, ps0, traces, ratings, jax.random.PRNGKey(2),
        checkpoint_cb=lambda ep, ps: None,
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(saved[2]),
        jax.tree_util.tree_leaves(res.pol_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
