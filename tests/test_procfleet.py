"""Process-isolated fleet: real subprocess replicas, SIGKILL chaos (ISSUE 9).

Acceptance for the process tier: a ``ProcessFleet`` spawns real
``serve-gateway`` subprocesses (ephemeral ports read from their
``gateway_listening`` lines), ``kill`` delivers a REAL SIGKILL that the
supervisor recovers from with capped deterministic backoff on the
original ports, per-replica pid/RSS/restart columns land in fleet stats
and the warehouse fleet view, and the end-to-end chaos bench (slow,
TLS + auth + persistent wire) holds availability and bit-exactness
through an OS-delivered process death.
"""

import asyncio
import json
import time

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.serve import (
    FleetRouter,
    ProcessFleet,
    RetryPolicy,
    export_policy_bundle,
)
from p2pmicrogrid_tpu.train import init_policy_state

A = 3


def _make_bundle(tmp_path, seed, name):
    cfg = default_config(
        sim=SimConfig(n_agents=A),
        train=TrainConfig(implementation="tabular", seed=seed),
    )
    ps = init_policy_state(cfg, jax.random.PRNGKey(seed))
    ps = ps._replace(
        q_table=jax.random.normal(
            jax.random.PRNGKey(seed + 1), ps.q_table.shape
        )
    )
    return export_policy_bundle(cfg, ps, str(tmp_path / name))


def _obs(n, seed=0):
    rng = np.random.default_rng(seed)
    obs = np.empty((n, A, 4), dtype=np.float32)
    obs[..., 0] = rng.uniform(0, 1, (n, A))
    obs[..., 1:] = rng.uniform(-1, 1, (n, A, 3))
    return obs


class TestProcessFleetUnits:
    def test_tls_pair_validated(self):
        with pytest.raises(ValueError):
            ProcessFleet(["b"], tls_cert="cert.pem")  # key missing

    def test_replica_floor(self):
        with pytest.raises(ValueError):
            ProcessFleet(["b"], n_replicas=0)

    def test_child_argv_shape(self):
        fleet = ProcessFleet(
            ["/bundles/b1"], mux=True, auth_secret_file="/s",
            tls_cert="/c.pem", tls_key="/k.pem",
            fault_plan_file="/plan.json",
        )
        argv = fleet._child_argv("replica-3", 8441, 8442, restarts=2)
        joined = " ".join(argv)
        assert "serve-gateway" in joined
        assert "--bundle /bundles/b1" in joined
        assert "--port 8441" in joined
        assert "--mux-port 8442" in joined
        assert "--replica-id replica-3" in joined
        assert "--restarts 2" in joined
        assert "--tls-cert /c.pem" in joined
        assert "--auth-secret-file /s" in joined
        assert "--chaos-plan /plan.json" in joined


class TestWireCompareGuards:
    def test_wire_compare_refuses_request_fault_plan_any_mode(
        self, tmp_path
    ):
        """--wire-compare + a request-fault chaos plan is refused in BOTH
        fleet modes (the pre-pass would anchor replica-0's fault windows
        and shift its coin indices), before any fleet spins up."""
        from p2pmicrogrid_tpu import cli
        from p2pmicrogrid_tpu.serve import FaultEvent, FaultPlan

        plan = FaultPlan(
            seed=0,
            events=(FaultEvent(kind="error", rate=0.5),),
        )
        plan_path = tmp_path / "plan.json"
        plan_path.write_text(plan.to_json())
        bundle = _make_bundle(tmp_path, 0, "b1")
        for extra in ([], ["--process"]):
            with pytest.raises(SystemExit) as exc:
                cli.main([
                    "serve-bench", "--fleet", "--wire-compare",
                    "--chaos-plan", str(plan_path),
                    "--bundle", bundle, "--agents", str(A),
                ] + extra)
            assert "fault windows" in str(exc.value)


class TestProcessFleetLive:
    """One real subprocess replica: spawn, SIGKILL, supervised relaunch.

    Deliberately minimal (one replica, no TLS) to keep the child's
    startup inside tier-1 budget; the full TLS+auth+chaos fleet runs in
    the slow end-to-end test below.
    """

    def test_sigkill_and_supervised_relaunch(self, tmp_path):
        bundle = _make_bundle(tmp_path, 0, "b1")
        fleet = ProcessFleet(
            [bundle], n_replicas=1, backoff_s=0.1, backoff_cap_s=1.0,
        )
        fleet.start()
        try:
            rep = fleet.replicas[0]
            assert rep.mux_port is not None
            router = FleetRouter(
                [rep], retry=RetryPolicy(max_attempts=4, deadline_s=20.0),
                fail_threshold=2, ok_threshold=1,
            )
            obs = _obs(1)[0]

            def act():
                async def run():
                    try:
                        return await router.act("house-1", obs)
                    finally:
                        await router.close_pools()

                return asyncio.run(run())

            first = act()
            assert first.status == 200
            pid_before = fleet.pid("replica-0")
            assert pid_before is not None

            fleet.kill("replica-0")
            assert fleet.pid("replica-0") is None  # REALLY dead
            assert fleet.kills == ["replica-0"]

            # The supervisor relaunches on the ORIGINAL ports; wait for
            # the fleet to answer again (child startup pays jax import +
            # engine warmup).
            end = time.monotonic() + 120.0
            recovered = False
            while time.monotonic() < end:
                if all(router.probe_once().values()):
                    recovered = True
                    break
                time.sleep(0.5)
            assert recovered, fleet.log_tail("replica-0")
            assert fleet.restarts == ["replica-0"]
            pid_after = fleet.pid("replica-0")
            assert pid_after is not None and pid_after != pid_before
            assert fleet.replicas[0].port == rep.port  # same address

            second = act()
            assert second.status == 200
            # Bit-exactness across the process death: same obs, same
            # bundle, identical actions from the relaunched process.
            assert second.actions == first.actions

            stats = router.fleet_stats()
            proc = stats["processes"]["replica-0"]
            assert proc["pid"] == pid_after
            assert proc["restarts"] == 1
            assert proc["rss_bytes"] > 0
        finally:
            fleet.stop_all()
        assert fleet.pid("replica-0") is None  # stop_all reaped the child


class TestFleetViewColumns:
    def test_warehouse_fleet_view_gains_wire_auth_process_columns(
        self, tmp_path
    ):
        from p2pmicrogrid_tpu.data import ResultsStore
        from p2pmicrogrid_tpu.telemetry import (
            SqliteSink,
            Telemetry,
            run_manifest,
        )

        db = str(tmp_path / "results.db")
        # An OLDER router run with a LONGER event stream: its final
        # fleet_stats has a higher per-run seq than the newer run's, so
        # ordering by seq across runs would wrongly pick it (review fix:
        # last_processes orders by ts, seq only breaks within-run ties).
        old = Telemetry(
            run_id="fleet-router-old",
            sinks=[SqliteSink(db)],
            manifest=run_manifest(
                extra={"config_hash": "cfg-abc", "serve_role": "router"}
            ),
        )
        for _ in range(50):
            old.event("noise")
        old.event(
            "fleet_stats",
            processes={"replica-0": {"pid": 999, "rss_bytes": 1,
                                     "restarts": 9}},
        )
        old.close()
        time.sleep(0.02)  # strictly newer ts for the second run
        tel = Telemetry(
            run_id="fleet-router-test",
            sinks=[SqliteSink(db)],
            manifest=run_manifest(
                extra={"config_hash": "cfg-abc", "serve_role": "router"}
            ),
        )
        tel.counter("router.reconnects", 3)
        tel.counter("router.auth_denied", 2)
        tel.event(
            "fleet_stats",
            n_replicas=2,
            n_healthy=2,
            processes={
                "replica-0": {"pid": 101, "rss_bytes": 1 << 20,
                              "restarts": 1},
                "replica-1": {"pid": 102, "rss_bytes": 1 << 20,
                              "restarts": 0},
            },
        )
        tel.close()
        store = ResultsStore(db)
        try:
            rows = store.query_fleet_view()
        finally:
            store.close()
        assert len(rows) == 1
        row = rows[0]
        assert row["config_hash"] == "cfg-abc"
        assert row["router_reconnects"] == 3
        assert row["router_auth_denied"] == 2
        # The NEWER run's processes win, not the older run's longer
        # (higher-seq) stream.
        assert row["last_processes"]["replica-0"]["pid"] == 101
        assert row["last_processes"]["replica-0"]["restarts"] == 1
        assert "replica-1" in row["last_processes"]


@pytest.mark.slow
class TestProcessChaosEndToEnd:
    def test_serve_bench_process_chaos_tls_auth(self, tmp_path, capfd):
        """The FLEET_PROC capture path end to end: real subprocess
        replicas with TLS + per-household tokens on the persistent wire,
        one replica SIGKILLed mid-run, supervisor relaunch — availability
        and bit-exactness asserted on the headline, 401 probe charged
        zero retry budget, and the persistent wire beats per-request
        connections on p95."""
        from p2pmicrogrid_tpu import cli

        bundle = _make_bundle(tmp_path, 0, "b1")
        rc = cli.main([
            "serve-bench", "--fleet", "--process", "--chaos",
            "--tls", "--auth", "--wire-compare",
            "--bundle", bundle,
            "--replicas", "2",
            "--requests", "192", "--rate", "64",
            "--kill-at", "0.9", "--restart-at", "2.2",
            "--agents", str(A),
        ])
        assert rc == 0
        lines = [
            json.loads(l)
            for l in capfd.readouterr().out.splitlines()
            if l.strip().startswith("{")
        ]
        headline = next(
            r for r in lines if r.get("metric") == "serve_bench_fleet"
        )
        compare = next(
            r for r in lines if r.get("metric") == "wire_comparison"
        )
        # The acceptance bars (ISSUE 9).
        assert headline["process_mode"] is True
        assert headline["tls"] is True
        assert headline["availability"] >= 0.99
        assert headline["bit_exact"] is True
        assert headline["chaos"]["kills"] == ["replica-1"]
        # The supervisor relaunch is visible per replica.
        assert headline["processes"]["replica-1"]["restarts"] >= 1
        assert headline["processes"]["replica-0"]["restarts"] == 0
        pids = {p["pid"] for p in headline["processes"].values()}
        assert len(pids) == 2  # real process isolation: distinct pids
        # Auth: unauthenticated probe rejected 401, no retries, no budget.
        probe = headline["auth_probe"]
        assert probe["n_401"] == probe["requests"] > 0
        assert probe["retries"] == 0
        assert probe["budget_spent"] == 0
        assert headline["auth_shed_rate"] > 0.0
        # Persistent wire beats the per-request-connection client on p95.
        assert compare["value"] > 1.0
        assert compare["mux_p95_ms"] < compare["http_p95_ms"]
