"""Million-household scale tier (ROADMAP item 4, ISSUE 17): synthetic
population determinism/skew/churn, the integer-nanosecond virtual clock's
exactness at 100k+ rps, per-replica warehouse shard federation (merge
idempotency, out-of-order shards, torn last batch, row-identical federated
views through the CLI), the structural O(1)-per-request audits at 1M ids
(router pins, registry stats, session ring), the LRU spill policy and the
SCALE_*.jsonl capture contract. Fast and JAX_PLATFORMS=cpu-safe (tier-1):
everything here is host-side numpy + sqlite — no engine compiles."""

import json
import sqlite3
from collections import deque

import numpy as np
import pytest

from p2pmicrogrid_tpu.data.results import (
    CONTINUOUS_VIEW_SQL,
    FLEET_VIEW_SQL,
    merge_warehouse_shards,
    shard_db_path,
)
from p2pmicrogrid_tpu.scale import (
    Population,
    PopulationConfig,
    audit_registry_scalability,
    audit_ring_scalability,
    audit_router_scalability,
    run_scale_audit,
    serve_bench_scale,
)
from p2pmicrogrid_tpu.scale.audit import _NoIterDict, audit_session_ring
from p2pmicrogrid_tpu.scale.bench import _simulate_lru_spill
from p2pmicrogrid_tpu.serve.loadgen import (
    _MAX_EXACT_NS,
    bursty_arrivals,
    gaps_to_schedule_ns,
    poisson_arrivals,
    schedule_ns_to_s,
)
from p2pmicrogrid_tpu.serve.registry import BundleRegistry
from p2pmicrogrid_tpu.serve.router import ConsistentHashRing, FleetRouter, Replica

N_SMALL = 10_000          # population for the statistical tests
N_MILLION = 1_000_000     # the scale the audits must hold at


# -- synthetic population ------------------------------------------------------


class TestPopulation:
    def test_same_config_same_requests_bit_for_bit(self):
        a = Population(n_households=N_SMALL, seed=7)
        b = Population(n_households=N_SMALL, seed=7)
        np.testing.assert_array_equal(
            a.sample(5_000, seed=3), b.sample(5_000, seed=3)
        )
        assert a.arrival_ids(64, seed=1) == b.arrival_ids(64, seed=1)

    def test_ids_are_stable_and_zero_padded(self):
        pop = Population(n_households=N_SMALL, seed=0)
        assert Population.household_id(42) == "house-0000042"
        assert pop.ids(np.array([0, 9_999])) == [
            "house-0000000", "house-0009999"
        ]
        # Stable under a DIFFERENT sampling history: ids are a pure
        # function of the index, never of draw order.
        pop.sample(1_000, seed=9)
        assert pop.ids(np.array([42])) == ["house-0000042"]

    def test_schedule_seeds_are_independent_streams(self):
        pop = Population(n_households=N_SMALL, seed=0)
        s1 = pop.sample(2_000, seed=1)
        s2 = pop.sample(2_000, seed=2)
        assert not np.array_equal(s1, s2)
        np.testing.assert_array_equal(s1, pop.sample(2_000, seed=1))

    def test_zipf_mix_concentrates_above_uniform(self):
        """With zipf_s > 0 the hottest 1% of ids carries well more than
        1% of traffic; at s=0 (uniform) it does not."""
        one_class = {"residential": (1.0, 1.0)}  # isolate the Zipf axis
        skewed = Population(n_households=N_SMALL, seed=0, zipf_s=0.9,
                            churn=0.0, rate_classes=one_class)
        flat = Population(n_households=N_SMALL, seed=0, zipf_s=0.0,
                          churn=0.0, rate_classes=one_class)
        n = 50_000
        hot = skewed.skew_summary(skewed.sample(n, seed=5))
        cold = flat.skew_summary(flat.sample(n, seed=5))
        assert hot["top1pct_share"] > 3 * cold["top1pct_share"]
        assert hot["unique"] < cold["unique"]

    def test_churn_widens_the_touched_id_set(self):
        base = Population(n_households=N_SMALL, seed=0, zipf_s=1.2,
                          churn=0.0)
        churny = Population(n_households=N_SMALL, seed=0, zipf_s=1.2,
                            churn=0.3)
        n = 30_000
        assert (
            churny.skew_summary(churny.sample(n, seed=2))["unique"]
            > base.skew_summary(base.sample(n, seed=2))["unique"]
        )

    def test_rate_classes_cover_population_and_validate(self):
        pop = Population(n_households=2_000, seed=1)
        names = {pop.rate_class(i) for i in range(2_000)}
        assert names == {"residential", "commercial", "industrial"}
        with pytest.raises(ValueError, match="shares must sum to 1"):
            PopulationConfig(
                n_households=10,
                rate_classes={"a": (0.5, 1.0), "b": (0.2, 2.0)},
            )
        with pytest.raises(ValueError, match="churn"):
            PopulationConfig(n_households=10, churn=1.5)
        with pytest.raises(ValueError, match="zipf_s"):
            PopulationConfig(n_households=10, zipf_s=-0.1)

    def test_sample_indices_always_in_range(self):
        pop = Population(n_households=100, seed=3, churn=0.5)
        idx = pop.sample(10_000, seed=1)
        assert idx.min() >= 0 and idx.max() < 100


# -- integer-nanosecond virtual clock ------------------------------------------


class TestVirtualClockExactness:
    def test_poisson_schedule_is_ns_exact_at_100k_rps(self):
        """The headline regime (100k rps x minutes of virtual time): the
        float64 seconds the planner consumes round-trip EXACTLY to the
        int64 nanosecond schedule — no cumsum drift at any arrival."""
        arr = poisson_arrivals(100_000.0, 300_000, seed=1)
        ns = np.rint(arr * 1e9).astype(np.int64)
        assert np.all(np.diff(ns) >= 1), "schedule must strictly increase"
        rng = np.random.default_rng(1)
        gaps = rng.exponential(1.0 / 100_000.0, size=300_000)
        np.testing.assert_array_equal(ns, gaps_to_schedule_ns(gaps))
        # ~3 virtual seconds of offered load actually materialized.
        assert 2.8 < arr[-1] < 3.2

    def test_zero_gaps_get_the_one_ns_floor(self):
        t = gaps_to_schedule_ns(np.zeros(5))
        np.testing.assert_array_equal(t, np.arange(1, 6))

    def test_overflow_past_exact_float64_range_is_loud(self):
        big = np.array([float(_MAX_EXACT_NS) / 1e9])
        with pytest.raises(OverflowError):
            gaps_to_schedule_ns(big)
        with pytest.raises(OverflowError):
            schedule_ns_to_s(np.array([_MAX_EXACT_NS], dtype=np.int64))

    def test_roundtrip_is_lossless_within_range(self):
        t_ns = np.array([1, 2, 10**9, 10**14, _MAX_EXACT_NS - 1],
                        dtype=np.int64)
        s = schedule_ns_to_s(t_ns)
        np.testing.assert_array_equal(
            np.rint(s * 1e9).astype(np.int64), t_ns
        )

    def test_bursty_arrivals_deterministic_and_strictly_increasing(self):
        a = bursty_arrivals(50_000.0, 100_000, seed=4)
        b = bursty_arrivals(50_000.0, 100_000, seed=4)
        np.testing.assert_array_equal(a, b)
        ns = np.rint(a * 1e9).astype(np.int64)
        assert np.all(np.diff(ns) >= 1)


# -- warehouse shard federation ------------------------------------------------


def _write_shard(path, shard_id, config_hash, run_id, events=8,
                 failovers=2.0):
    """One replica's warehouse shard through the REAL WAL-mode sink:
    serve-role run manifest, serve_request traces and a router counter —
    the rows every federated view aggregates."""
    from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

    tel = Telemetry(
        run_id=run_id,
        sinks=[SqliteSink(path, batch=4, shard_id=shard_id)],
        manifest={
            "created": "2026-08-01T00:00:00", "config_hash": config_hash,
            "git_rev": "rev-1", "setting": "2-agent", "backend": "cpu",
            "device_count": 1, "serve_role": "replica",
            "serve_batching": "continuous",
        },
    )
    for i in range(events):
        tel.event("serve_request", wait_ms=0.5 + i, latency_ms=1.0 + i)
    tel.counter("router.failovers", failovers)
    tel.close()


def _all_rows(con):
    """Every merged table's full row set, as comparable sorted tuples."""
    from p2pmicrogrid_tpu.data.results import SHARD_MERGE_TABLES

    out = {}
    for table in SHARD_MERGE_TABLES:
        out[table] = sorted(
            tuple(r) for r in con.execute(f"SELECT * FROM {table}")
        )
    return out


class TestShardMerge:
    def _shards(self, tmp_path, n=3):
        base = str(tmp_path / "results.db")
        paths = []
        for r in range(n):
            shard = shard_db_path(base, f"replica-{r}")
            _write_shard(shard, f"replica-{r}", "cfg-scale",
                         f"run-{r}", events=4 + r)
            paths.append(shard)
        return base, paths

    def test_shard_path_is_a_sibling_of_the_base_db(self, tmp_path):
        base = str(tmp_path / "results.db")
        assert shard_db_path(base, "replica-0") == str(
            tmp_path / "results.shard-replica-0.db"
        )

    def test_merge_is_idempotent_same_shard_twice(self, tmp_path):
        _base, paths = self._shards(tmp_path)
        con = sqlite3.connect(":memory:")
        try:
            merge_warehouse_shards(con, paths)
            before = _all_rows(con)
            again = merge_warehouse_shards(con, [paths[0], paths[0]])
            assert again["telemetry_runs"] == 0
            assert again["telemetry_points"] == 0
            assert _all_rows(con) == before
        finally:
            con.close()

    def test_merge_order_does_not_matter(self, tmp_path):
        _base, paths = self._shards(tmp_path)
        a = sqlite3.connect(":memory:")
        b = sqlite3.connect(":memory:")
        try:
            merge_warehouse_shards(a, paths)
            merge_warehouse_shards(b, list(reversed(paths)))
            assert _all_rows(a) == _all_rows(b)
        finally:
            a.close()
            b.close()

    def test_torn_last_batch_merges_to_committed_prefix(self, tmp_path):
        """A SIGKILLed replica's shard: the sink flushed one full batch
        and died with another buffered. The committed prefix federates
        cleanly — no half-rows, no merge error."""
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        shard = str(tmp_path / "torn.shard-replica-9.db")
        tel = Telemetry(
            run_id="run-torn",
            sinks=[SqliteSink(shard, batch=3, shard_id="replica-9")],
            manifest={
                "created": "2026-08-01T00:00:00",
                "config_hash": "cfg-torn", "git_rev": "rev-1",
                "setting": "2-agent", "backend": "cpu", "device_count": 1,
                "serve_role": "replica",
            },
        )
        for i in range(4):  # one batch of 3 commits; the 4th stays buffered
            tel.event("serve_request", wait_ms=float(i))
        # No tel.close(): the buffered event dies with the "process".
        con = sqlite3.connect(":memory:")
        try:
            stats = merge_warehouse_shards(con, [shard])
            assert stats["shards"] == 1
            (n,) = con.execute(
                "SELECT COUNT(*) FROM telemetry_points "
                "WHERE kind = 'serve_request'"
            ).fetchone()
            assert n == 3  # exactly the committed batch, never a half-row
        finally:
            con.close()
        tel.close()  # release the sink for tmp_path cleanup

    def test_federated_views_row_identical_to_single_db(self, tmp_path):
        """The acceptance criterion: `telemetry-query` over N shards
        returns the SAME fleet/continuous rows as the single-DB funnel
        holding every replica's telemetry."""
        _base, paths = self._shards(tmp_path)
        funnel = str(tmp_path / "funnel.db")
        con = sqlite3.connect(funnel)
        try:
            merge_warehouse_shards(con, paths)
        finally:
            con.close()
        federated = sqlite3.connect(":memory:")
        single = sqlite3.connect(funnel)
        try:
            merge_warehouse_shards(federated, paths)
            for sql in (FLEET_VIEW_SQL, CONTINUOUS_VIEW_SQL):
                fed = federated.execute(sql).fetchall()
                fun = single.execute(sql).fetchall()
                assert fed == fun
                assert fed, "view must aggregate real rows, not be vacuous"
        finally:
            federated.close()
            single.close()

    def test_cli_shard_federation_matches_results_db(self, tmp_path, capsys):
        from p2pmicrogrid_tpu.cli import main

        _base, paths = self._shards(tmp_path)
        funnel = str(tmp_path / "funnel.db")
        con = sqlite3.connect(funnel)
        try:
            merge_warehouse_shards(con, paths)
        finally:
            con.close()
        for view_flag in ("--fleet", "--continuous"):
            shard_args = ["telemetry-query", view_flag]
            for p in reversed(paths):  # out-of-order on purpose
                shard_args += ["--shard", p]
            assert main(shard_args) == 0
            shard_out = capsys.readouterr().out.strip().splitlines()
            assert main(
                ["telemetry-query", view_flag, "--results-db", funnel]
            ) == 0
            db_out = capsys.readouterr().out.strip().splitlines()
            assert [json.loads(l) for l in shard_out] == [
                json.loads(l) for l in db_out
            ]
            assert shard_out, f"{view_flag} federation returned no rows"

    def test_cli_refuses_compact_and_watch_with_shards(self, tmp_path,
                                                       capsys):
        from p2pmicrogrid_tpu.cli import main

        _base, paths = self._shards(tmp_path, n=1)
        for extra in ("--compact", "--watch"):
            rc = main(["telemetry-query", "--shard", paths[0], extra])
            capsys.readouterr()
            assert rc == 2
        assert main(["telemetry-query"]) == 2  # neither source given
        capsys.readouterr()


# -- structural O(1) audits at 1M ids ------------------------------------------


class _FakeEngine:
    def __init__(self, config_hash):
        self.manifest = {"config_hash": config_hash,
                         "implementation": "fake"}
        self.n_agents = 1
        self.stats = {"rows": 0, "batches": 0, "padded_rows": 0}


class _FakeQueue:
    depth = 0
    recent_wait_ms = deque()


@pytest.fixture(scope="module")
def million_pins():
    """1M household->bundle pins, built ONCE for the audits below (the
    dict build is the expensive part, not the audited operations)."""
    return {
        f"h{i}": ("cfg-b" if i % 3 == 0 else "cfg-a")
        for i in range(N_MILLION)
    }


class TestScaleAudits:
    def test_noiterdict_trips_on_iteration_and_allows_scoped(self):
        d = _NoIterDict({"a": 1, "b": 2})
        with pytest.raises(AssertionError, match="O\\(1\\) audit tripped"):
            list(d)
        with pytest.raises(AssertionError):
            dict(d)  # dict() copies via keys() — also an id-space scan
        assert d["a"] == 1 and len(d) == 2 and "b" in d  # O(1) ops fine
        with d.allow():
            assert sorted(d.items()) == [("a", 1), ("b", 2)]

    def test_ring_audit_structure_and_spread(self):
        ring = ConsistentHashRing(vnodes=512)
        for r in range(5):
            ring.add(f"replica-{r}")
        ids = [f"house-{i:07d}" for i in range(20_000)]
        audit = audit_ring_scalability(ring, ids, tolerance=0.25)
        assert audit["ring_points"] == 5 * 512
        assert audit["within_tolerance"]

    def test_ring_audit_rejects_household_sized_tables(self):
        ring = ConsistentHashRing(vnodes=8)
        ring.add("replica-0")
        ring._points.append(ring._points[-1] + 1)  # table leaked an entry
        ring._owners.append("replica-0")
        with pytest.raises(AssertionError, match="replicas x vnodes"):
            audit_ring_scalability(ring, ["house-0000001"])

    def test_registry_stats_never_iterates_a_million_pins(self,
                                                          million_pins):
        """Satellite (f) regression: stats() at 1M pinned households is
        O(bundles) — the _NoIterDict raises if it ever re-scans the
        id-keyed pin map, and the incremental tallies must agree with the
        map's true size."""
        reg = BundleRegistry()
        reg.register(_FakeEngine("cfg-a"), _FakeQueue(), default=True)
        reg.register(_FakeEngine("cfg-b"), _FakeQueue())
        n_b = sum(1 for v in million_pins.values() if v == "cfg-b")
        with reg._lock:
            reg._pins = dict(million_pins)
            reg._pin_counts = {"cfg-a": N_MILLION - n_b, "cfg-b": n_b}
        audit = audit_registry_scalability(reg)
        assert audit["pinned_total"] == N_MILLION
        snap = reg.stats()
        assert snap["bundles"]["cfg-b"]["pinned_households"] == n_b
        assert reg.pinned_count == N_MILLION

    def test_registry_route_path_is_o1_under_split(self):
        reg = BundleRegistry()
        reg.register(_FakeEngine("cfg-a"), _FakeQueue(), default=True)
        reg.register(_FakeEngine("cfg-b"), _FakeQueue())
        reg.set_split("cfg-b", 50)
        with reg._lock:
            reg._pins = _NoIterDict(reg._pins)
        for i in range(64):  # pin writes must never scan the pin map
            reg.route(f"house-{i:07d}")
        assert reg.pinned_count == 64

    def test_router_fleet_stats_reports_count_not_map(self, million_pins):
        """Satellite (f) regression: fleet_stats() at 1M pins returns the
        O(1) count — never a materialized per-household map — and the
        request-path bookkeeping stays O(1) under the _NoIterDict."""
        router = FleetRouter(
            [Replica(replica_id=f"replica-{r}", host="127.0.0.1", port=1)
             for r in range(3)],
            vnodes=64,
        )
        guard = _NoIterDict(million_pins)
        with router._lock:
            router._pins = guard
        snap = router.fleet_stats(timeout_s=0.2)
        assert snap["pinned_households"] == N_MILLION
        assert isinstance(snap["pinned_households"], int)
        assert router.pinned_count == N_MILLION
        # Hand the audit a plain dict — it plants its own tripwire.
        with router._lock, guard.allow():
            router._pins = dict(guard)
        audit = audit_router_scalability(router, snapshot_limit=100)
        assert audit["snapshot_len"] <= 100

    def test_router_pinned_snapshot_is_capped(self):
        router = FleetRouter(
            [Replica(replica_id=f"replica-{r}", host="127.0.0.1", port=1)
             for r in range(2)],
            vnodes=32,
        )
        with router._lock:
            router._pins = {f"h{i}": "replica-0" for i in range(500)}
        assert len(router.pinned_households(limit=50)) == 50
        assert router.pinned_count == 500

    def test_run_scale_audit_holds_at_a_million_ids(self):
        """The ISSUE's structural claim end-to-end: population, rings at
        3/10/30 replicas and the pin-guarded router, all at 1M ids."""
        audit = run_scale_audit(
            n_households=N_MILLION, sample=20_000, vnodes=1024,
            replica_counts=(3, 10, 30), seed=0,
        )
        assert audit["n_households"] == N_MILLION
        assert [r["replicas"] for r in audit["rings"]] == [3, 10, 30]
        assert all(r["within_tolerance"] for r in audit["rings"])
        assert audit["router"]["pins"] == 0  # probe cleaned up after itself
        assert 0 < audit["population_skew"]["unique"] <= 20_000


# -- session-ring spill policy -------------------------------------------------


class TestSpillPolicy:
    def test_lru_replay_counts_hits_evictions_rejoins(self):
        seq = np.array([1, 2, 1, 3, 2, 1])  # slots=2: 3 evicts, 2 rejoins
        out = _simulate_lru_spill(seq, max_slots=2)
        assert out == {
            "requests": 6, "hits": 1, "joins": 5,
            "evictions": 3, "rejoins": 2,
        }

    def test_lru_replay_is_deterministic(self):
        pop = Population(n_households=1_000, seed=2)
        seq = pop.sample(5_000, seed=1)
        assert (_simulate_lru_spill(seq, 64)
                == _simulate_lru_spill(seq.copy(), 64))

    def test_batcher_counts_spill_rejoins_and_stays_bounded(self):
        """The live continuous batcher mirrors the replay's accounting:
        an evicted household's return is a counted spill rejoin, and the
        host tables stay bounded by max_slots no matter the id churn."""
        from p2pmicrogrid_tpu.serve.continuous import ContinuousBatcher

        class _Engine:
            is_recurrent = False
            max_batch = 4
            n_agents = 1
            telemetry = None
            manifest = {"config_hash": "cfg-spill"}

            def bucket_for(self, n):
                return n

            def act(self, obs):
                return np.zeros((obs.shape[0], 1), dtype=np.float32)

        obs = np.zeros((1, 4), dtype=np.float32)
        with ContinuousBatcher(_Engine(), max_slots=1,
                               autostart=False) as cb:
            for h in ("a", "b", "a", "c", "b"):
                cb.submit(obs, household=h)
                cb.step_once()
            stats = dict(cb.stats)
            audit = audit_session_ring(cb)
        assert stats["evictions"] >= 3
        assert stats["spill_rejoins"] >= 2
        assert audit["resident"] <= 1
        assert audit["recently_evicted"] <= audit["recently_evicted_cap"]


# -- the scale bench + capture contract ----------------------------------------


@pytest.fixture(scope="module")
def scale_rows(tmp_path_factory):
    """One small-but-real serve_bench_scale run shared by the contract
    tests: explicit service model (no engine), real ring placement, real
    shard ingest into a real warehouse file."""
    db = str(tmp_path_factory.mktemp("scale") / "results.db")
    model = {1: 0.0004, 2: 0.0005, 4: 0.0007, 8: 0.0010}
    rows = serve_bench_scale(
        service_model=model,
        population=Population(n_households=5_000, seed=0),
        rate_hz=2_000.0, duration_s=1.0,
        replica_counts=(2, 3, 4), vnodes=256,
        max_batch=8, max_wait_s=0.002, max_slots=64,
        results_db=db, seed=0,
    )
    return rows, db


class TestScaleBench:
    def test_headline_is_last_and_carries_the_claims(self, scale_rows):
        rows, _db = scale_rows
        head = rows[-1]
        assert head["metric"] == "serve_bench_scale"
        assert head["households"] == 5_000
        assert head["replicas"] == 4
        for key in ("rps_per_replica", "p50_ms", "p99_ms",
                    "ingest_lag_ms", "load_spread", "value",
                    "vs_baseline"):
            assert isinstance(head[key], (int, float))
        assert head["ingest"]["measured"] is True
        assert head["ingest"]["merged_rows"]["telemetry_points"] > 0

    def test_sweep_and_scaling_rows_cover_every_replica_count(
        self, scale_rows
    ):
        rows, _db = scale_rows
        sweep = [r for r in rows if r["metric"] == "scale_replica_sweep"]
        assert [r["replicas"] for r in sweep] == [2, 3, 4]
        (scaling,) = [r for r in rows if r["metric"] == "scale_scaling"]
        assert scaling["replica_counts"] == [2, 3, 4]
        assert set(scaling["load_spread_by_count"]) == {"2", "3", "4"}
        (spill,) = [r for r in rows if r["metric"] == "scale_spill"]
        assert spill["max_slots"] == 64
        assert 0.0 <= spill["hit_rate"] <= 1.0

    def test_bench_is_deterministic(self):
        kw = dict(
            service_model={1: 0.0004, 2: 0.0005},
            population=Population(n_households=500, seed=1),
            rate_hz=500.0, duration_s=1.0, replica_counts=(2, 3, 4),
            vnodes=64, max_batch=2, seed=3,
        )
        assert serve_bench_scale(**kw) == serve_bench_scale(**kw)

    def test_shard_files_merge_into_the_base_db(self, scale_rows):
        _rows, db = scale_rows
        con = sqlite3.connect(db)
        try:
            (n,) = con.execute(
                "SELECT COUNT(*) FROM telemetry_points "
                "WHERE kind = 'scale_batch'"
            ).fetchone()
        finally:
            con.close()
        assert n > 0

    def test_schema_checker_enforces_the_scale_contract(self, scale_rows,
                                                        tmp_path):
        import sys

        sys.path.insert(0, "tools")
        try:
            from check_artifacts_schema import check_scale_jsonl
        finally:
            sys.path.pop(0)
        rows, _db = scale_rows

        def write(path, rs):
            with open(path, "w") as f:
                for r in rs:
                    f.write(json.dumps(r) + "\n")
            return str(path)

        # A committed-grade capture (headline claims 1M households).
        good = [dict(r) for r in rows]
        good[-1]["households"] = 1_000_000
        problems = []
        check_scale_jsonl(write(tmp_path / "SCALE_ok.jsonl", good),
                          problems)
        assert problems == []
        # Under-scale capture: flagged.
        problems = []
        check_scale_jsonl(write(tmp_path / "SCALE_small.jsonl", rows),
                          problems)
        assert any("households" in p for p in problems)
        # Headline not last: flagged.
        problems = []
        check_scale_jsonl(
            write(tmp_path / "SCALE_mid.jsonl", [good[-1]] + good[:-1]),
            problems,
        )
        assert any("last row" in p for p in problems)
        # Missing scaling sweep: flagged.
        problems = []
        no_scaling = [r for r in good if r["metric"] != "scale_scaling"]
        check_scale_jsonl(
            write(tmp_path / "SCALE_nosweep.jsonl", no_scaling), problems
        )
        assert any("scale_scaling" in p for p in problems)


# -- satellite defaults --------------------------------------------------------


class TestScaleDefaults:
    def test_promotion_default_batching_is_continuous(self):
        import inspect

        from p2pmicrogrid_tpu.serve.promotion import run_promotion_pipeline

        sig = inspect.signature(run_promotion_pipeline)
        assert sig.parameters["batching"].default == "continuous"

    def test_fleet_loadgen_rejects_mismatched_household_ids(self):
        from p2pmicrogrid_tpu.serve.router import run_fleet_loadgen

        import asyncio

        with pytest.raises(ValueError, match="household_ids"):
            asyncio.run(
                run_fleet_loadgen(
                    None,
                    np.zeros((4, 1, 4), dtype=np.float32),
                    np.array([0.0, 0.001, 0.002, 0.003]),
                    households=["house-0000001"],
                    household_ids=["only-one"],
                )
            )
