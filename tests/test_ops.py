"""Unit tests for the pure physics/market core against hand-computed oracles.

Oracles are transliterated NumPy implementations of the reference formulas
(cited per test) evaluated on small concrete inputs — the closed-form pieces
SURVEY.md section 4 identifies as the natural test seams.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from p2pmicrogrid_tpu.config import (
    BatteryConfig,
    QLearningConfig,
    TariffConfig,
    ThermalConfig,
)
from p2pmicrogrid_tpu.ops.thermal import thermal_step, comfort_penalty, normalized_temperature
from p2pmicrogrid_tpu.ops.tariff import grid_prices, p2p_price
from p2pmicrogrid_tpu.ops.market import clear_market, compute_costs, divide_power, zero_diagonal
from p2pmicrogrid_tpu.ops.battery import battery_step, battery_rule_update, available_energy, available_space
from p2pmicrogrid_tpu.ops.obs import make_observation, discretize

DT = 900.0  # 15-minute slot in seconds (setup.py:16)


def ref_thermal(cfg: ThermalConfig, t_out, t_in, t_bm, hp_power, solar=0.0):
    """NumPy oracle of heating.py:37-56."""
    d_tin = (1 / cfg.ci) * (
        (t_bm - t_in) / cfg.ri + (t_out - t_in) / cfg.rvent + (1 - cfg.f_rad) * hp_power * cfg.cop
    )
    d_tbm = (1 / cfg.cm) * (
        (t_in - t_bm) / cfg.ri + (t_out - t_bm) / cfg.re + cfg.ga * solar + cfg.f_rad * hp_power * cfg.cop
    )
    return t_in + d_tin * DT, t_bm + d_tbm * DT


class TestThermal:
    def test_matches_reference_formula(self):
        cfg = ThermalConfig()
        t_in, t_bm = thermal_step(cfg, DT, 5.0, 21.0, 20.5, 1500.0)
        exp_in, exp_bm = ref_thermal(cfg, 5.0, 21.0, 20.5, 1500.0)
        np.testing.assert_allclose(float(t_in), exp_in, rtol=1e-6)
        np.testing.assert_allclose(float(t_bm), exp_bm, rtol=1e-6)

    def test_no_heating_cools_toward_outdoor(self):
        cfg = ThermalConfig()
        t_in, t_bm = 21.0, 21.0
        for _ in range(96):
            t_in, t_bm = thermal_step(cfg, DT, 0.0, t_in, t_bm, 0.0)
        assert float(t_in) < 21.0

    def test_heating_raises_temperature(self):
        cfg = ThermalConfig()
        cold_in, _ = thermal_step(cfg, DT, 5.0, 20.0, 20.0, 0.0)
        warm_in, _ = thermal_step(cfg, DT, 5.0, 20.0, 20.0, 3000.0)
        assert float(warm_in) > float(cold_in)

    def test_batched_shapes(self):
        cfg = ThermalConfig()
        t_in = jnp.full((4, 8), 21.0)
        t_out = jnp.full((4, 8), 5.0)
        out_in, out_bm = thermal_step(cfg, DT, t_out, t_in, t_in, jnp.zeros((4, 8)))
        assert out_in.shape == (4, 8) and out_bm.shape == (4, 8)

    def test_comfort_penalty_offset(self):
        """agent.py:225-232: zero in band, excess + 1 outside."""
        cfg = ThermalConfig()  # band [20, 22]
        t = jnp.array([21.0, 20.0, 22.0, 19.5, 22.5, 18.0])
        pen = comfort_penalty(cfg, t)
        np.testing.assert_allclose(
            np.asarray(pen), [0.0, 0.0, 0.0, 1.5, 1.5, 3.0], atol=1e-6
        )

    def test_normalized_temperature(self):
        cfg = ThermalConfig()
        np.testing.assert_allclose(
            np.asarray(normalized_temperature(cfg, jnp.array([20.0, 21.0, 22.5]))),
            [-1.0, 0.0, 1.5],
            atol=1e-6,
        )


class TestTariff:
    def test_curve_values(self):
        """agent.py:59-67: buy = (12 + 5 sin(t * 4*pi - 3)) / 100."""
        cfg = TariffConfig()
        t = jnp.array([0.0, 0.25, 0.5, 0.8])
        buy, inj = grid_prices(cfg, t)
        expected = (12.0 + 5.0 * np.sin(np.asarray(t) * 4 * np.pi - 3.0)) / 100.0
        np.testing.assert_allclose(np.asarray(buy), expected, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(inj), 0.07, rtol=1e-6)

    def test_p2p_midpoint(self):
        assert float(p2p_price(jnp.array(0.17), jnp.array(0.07))) == pytest.approx(0.12)


class TestMarket:
    def test_two_agent_opposite_signs_match(self):
        """community.py:45-54 on a hand-worked 2-agent case: agent 0 wants to
        buy 100 W from agent 1; agent 1 offers 250 W. Matched = 100."""
        p2p = jnp.array([[0.0, 100.0], [-250.0, 0.0]])
        p_grid, p_p2p = clear_market(p2p)
        np.testing.assert_allclose(np.asarray(p_p2p), [100.0, -100.0], atol=1e-5)
        np.testing.assert_allclose(np.asarray(p_grid), [0.0, -150.0], atol=1e-5)

    def test_same_sign_no_match(self):
        p2p = jnp.array([[0.0, 100.0], [250.0, 0.0]])
        p_grid, p_p2p = clear_market(p2p)
        np.testing.assert_allclose(np.asarray(p_p2p), [0.0, 0.0], atol=1e-5)
        np.testing.assert_allclose(np.asarray(p_grid), [100.0, 250.0], atol=1e-5)

    def test_three_agent_conservation(self):
        """Total power is conserved: sum(p_grid + p_p2p) == sum(p2p)."""
        rng = np.random.default_rng(0)
        p2p = jnp.asarray(rng.normal(size=(3, 3)) * 1e3)
        p2p = zero_diagonal(p2p)
        p_grid, p_p2p = clear_market(p2p)
        np.testing.assert_allclose(
            float(jnp.sum(p_grid + p_p2p)), float(jnp.sum(p2p)), rtol=1e-5
        )

    def test_p2p_exchange_antisymmetric(self):
        rng = np.random.default_rng(1)
        p2p = zero_diagonal(jnp.asarray(rng.normal(size=(5, 5)) * 1e3))
        _, p_p2p = clear_market(p2p)
        # Every matched trade has an equal and opposite counterparty.
        assert float(jnp.sum(p_p2p)) == pytest.approx(0.0, abs=1e-3)

    def test_costs_hand_computed(self):
        """community.py:56-65: 1 kW from grid for 15 min at 0.12 €/kWh = 0.03 €."""
        cost = compute_costs(
            p_grid=jnp.array([1000.0, -1000.0]),
            p_p2p=jnp.array([0.0, 0.0]),
            buy_price=jnp.array(0.12),
            injection_price=jnp.array(0.07),
            p2p_price=jnp.array(0.095),
            slot_hours=0.25,
        )
        np.testing.assert_allclose(np.asarray(cost), [0.03, -0.0175], rtol=1e-6)

    def test_divide_power_proportional(self):
        """agent.py:186-195: buying 300 W with sellers offering -100/-200 W
        splits 100/200; the same-sign counterparty gets nothing."""
        out = jnp.array(300.0)
        powers = jnp.array([-100.0, -200.0, 50.0])
        p = divide_power(out, powers)
        np.testing.assert_allclose(np.asarray(p), [100.0, 200.0, 0.0], atol=1e-4)

    def test_divide_power_equal_split_fallback(self):
        out = jnp.array(300.0)
        powers = jnp.array([100.0, 200.0, 0.0])
        # sign(0) == 0 != sign(300) so the zero entry *is* "filtered" but
        # contributes 0 to the total -> equal-split branch (agent.py:190-191).
        p = divide_power(out, powers)
        np.testing.assert_allclose(np.asarray(p), [100.0, 100.0, 100.0], atol=1e-4)

    def test_divide_power_no_nan_under_jit(self):
        f = jax.jit(divide_power)
        p = f(jnp.array(0.0), jnp.zeros(4))
        assert not bool(jnp.any(jnp.isnan(p)))


class TestBattery:
    def test_sqrt_efficiency_roundtrip(self):
        """storage.py:60-64: charging e then discharging recovers eta * e."""
        cfg = BatteryConfig(enabled=True, efficiency=0.81, init_soc=0.5)
        soc = jnp.array(0.5)
        soc2, p_in = battery_step(cfg, soc, jnp.array(1000.0), DT)
        # SoC rose by sqrt(eta) * e / cap
        expected = 0.5 + np.sqrt(0.81) * 1000.0 * DT / cfg.capacity
        np.testing.assert_allclose(float(soc2), expected, rtol=1e-6)
        soc3, p_out = battery_step(cfg, soc2, jnp.array(-1000.0 * 0.81), DT)
        np.testing.assert_allclose(float(soc3), 0.5, atol=1e-6)

    def test_respects_soc_limits(self):
        cfg = BatteryConfig(enabled=True, max_soc=0.9, min_soc=0.1)
        soc_full, _ = battery_step(cfg, jnp.array(0.9), jnp.array(5e3), DT)
        assert float(soc_full) == pytest.approx(0.9)
        soc_empty, _ = battery_step(cfg, jnp.array(0.1), jnp.array(-5e3), DT)
        assert float(soc_empty) == pytest.approx(0.1)

    def test_rule_update_covers_deficit(self):
        """agent.py:138-153: positive balance is covered from the battery."""
        cfg = BatteryConfig(enabled=True)
        soc, bal = battery_rule_update(cfg, jnp.array(0.5), jnp.array(500.0), DT)
        assert float(bal) == pytest.approx(0.0, abs=1e-4)
        assert float(soc) < 0.5

    def test_rule_update_stores_surplus(self):
        cfg = BatteryConfig(enabled=True)
        soc, bal = battery_rule_update(cfg, jnp.array(0.5), jnp.array(-500.0), DT)
        assert float(bal) == pytest.approx(0.0, abs=1e-4)
        assert float(soc) > 0.5

    def test_available_energy_space(self):
        cfg = BatteryConfig(enabled=True, efficiency=1.0)
        assert float(available_energy(cfg, jnp.array(0.1))) == pytest.approx(0.0)
        assert float(available_space(cfg, jnp.array(0.9))) == pytest.approx(0.0)


class TestObservation:
    def test_make_observation_order(self):
        obs = make_observation(
            jnp.array(0.5), jnp.array(-0.2), jnp.array(0.3), jnp.array(0.1)
        )
        np.testing.assert_allclose(np.asarray(obs), [0.5, -0.2, 0.3, 0.1], atol=1e-6)

    def test_discretize_matches_reference(self):
        """rl.py:89-95 oracle on hand inputs (including clamping)."""
        cfg = QLearningConfig()

        def ref_bins(s):
            time = max(min(int(s[0] * 20), 19), 0)
            temp = max(min(int((s[1] + 1) / 2 * 18 + 1), 19), 0)
            bal = max(min(int((s[2] + 1) / 2 * 20), 19), 0)
            p2p = max(min(int((s[3] + 1) / 2 * 20), 19), 0)
            return time, temp, bal, p2p

        cases = [
            [0.0, 0.0, 0.0, 0.0],
            [0.99, 1.0, 1.0, 1.0],
            [0.5, -1.0, -1.0, -1.0],
            [1.5, -3.0, 2.5, 0.01],  # out-of-range -> clamped
            [0.26, 0.13, -0.4, 0.77],
        ]
        for s in cases:
            got = discretize(cfg, jnp.asarray(s, dtype=jnp.float32))
            assert tuple(int(g) for g in got) == ref_bins(s), s

    def test_discretize_batched(self):
        cfg = QLearningConfig()
        obs = jnp.zeros((7, 3, 4))
        idx = discretize(cfg, obs)
        assert all(i.shape == (7, 3) for i in idx)
