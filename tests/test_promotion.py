"""Safe continual deployment: trace replay, gated promotion, canary rollback.

Tier-1 acceptance for ISSUE 10: warehouse serve traces replay back into
shape/dtype-exact replay buffers (refusing compacted runs loudly), the
continual driver fine-tunes an incumbent bundle into a distinct candidate,
the promotion gate's decision matrix holds (better/worse/tie on eval cost
x pass/fail SLO), a live canary abort restores the incumbent with zero
failed requests, token rotation verifies both secrets inside the grace
window, and health probes ride persistent mux connections. Fast and
JAX_PLATFORMS=cpu-safe by design.
"""

import dataclasses
import json
import os
import time

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.data.results import ResultsStore
from p2pmicrogrid_tpu.data.trace_export import (
    TraceDataset,
    TracesCompactedError,
    decision_cost,
    export_serve_traces,
    to_replay_state,
    trace_reward,
)
from p2pmicrogrid_tpu.serve import auth as serve_auth
from p2pmicrogrid_tpu.serve.engine import PolicyEngine
from p2pmicrogrid_tpu.serve.gateway import (
    AdmissionConfig,
    GatewayServer,
    build_gateway,
)
from p2pmicrogrid_tpu.serve.loadgen import synthetic_obs
from p2pmicrogrid_tpu.serve.promotion import (
    CanaryBudgets,
    GateBudgets,
    _drive_wire_stage,
    make_crafted_bundle,
    run_promotion_gate,
    run_promotion_pipeline,
)

A = 3  # community size for all promotion tests


def _cfg(seed=0, impl="tabular", **train_kw):
    return default_config(
        sim=SimConfig(n_agents=A),
        train=TrainConfig(implementation=impl, seed=seed, **train_kw),
    )


def _distinct_cfg(cfg, bump):
    """Same experiment, distinct config_hash (the registry/canary key) —
    the same episode-origin device train/continual.py uses."""
    return cfg.replace(
        train=dataclasses.replace(
            cfg.train, starting_episodes=cfg.train.starting_episodes + bump
        )
    )


@pytest.fixture(scope="module")
def crafted(tmp_path_factory):
    """Crafted bundles shared across the module (exports are cheap; the
    point of sharing is the engines tests build over them)."""
    root = tmp_path_factory.mktemp("promotion-bundles")
    cfg = _cfg()
    dirs = {"incumbent": make_crafted_bundle(
        cfg, "incumbent", str(root / "incumbent")
    )}
    for i, kind in enumerate(
        ("good", "cost_regressed", "nan_poisoned"), start=1
    ):
        dirs[kind] = make_crafted_bundle(
            _distinct_cfg(cfg, 100 + i), kind, str(root / kind)
        )
    # A tie candidate: the incumbent's exact table under a distinct hash.
    dirs["tie"] = make_crafted_bundle(
        _distinct_cfg(cfg, 200), "incumbent", str(root / "tie")
    )
    return cfg, dirs


_FAST = lambda i, j: 0.0005   # modeled 0.5 ms batches — inside any budget
_SLOW = lambda i, j: 0.25     # modeled 250 ms batches — over every budget


# -- promotion gate ------------------------------------------------------------


class TestPromotionGate:
    @pytest.mark.parametrize(
        "candidate,service,expect_pass,expect_reason",
        [
            ("good", _FAST, True, None),
            ("good", _SLOW, False, "p95"),
            ("cost_regressed", _FAST, False, "regresses"),
            ("cost_regressed", _SLOW, False, "regresses"),
            ("tie", _FAST, False, "ties"),
        ],
    )
    def test_decision_matrix(
        self, crafted, candidate, service, expect_pass, expect_reason
    ):
        """Better/worse/tie on eval cost x pass/fail SLO."""
        cfg, dirs = crafted
        verdict = run_promotion_gate(
            cfg, dirs[candidate], dirs["incumbent"],
            s_eval=4, bench_requests=64, max_batch=8,
            service_time_fn=service,
        )
        assert verdict.passed is expect_pass
        if expect_reason:
            assert any(expect_reason in r for r in verdict.reasons)
        if candidate == "good" and service is _SLOW:
            # The SLO failure must be the ONLY failure: the eval half
            # passed, so the matrix cells are independent.
            assert all("p9" in r for r in verdict.reasons)

    def test_nan_poisoned_blocked_on_params(self, crafted):
        cfg, dirs = crafted
        verdict = run_promotion_gate(
            cfg, dirs["nan_poisoned"], dirs["incumbent"],
            s_eval=4, bench_requests=64, max_batch=8,
            service_time_fn=_FAST,
        )
        assert not verdict.passed
        assert any("non-finite parameter" in r for r in verdict.reasons)

    def test_verdict_lands_in_warehouse(self, crafted, tmp_path):
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        cfg, dirs = crafted
        db = str(tmp_path / "wh.db")
        tel = Telemetry(
            run_id="gate-test", sinks=[SqliteSink(db)],
            manifest={"config_hash": "gate-test"},
        )
        run_promotion_gate(
            cfg, dirs["good"], dirs["incumbent"], telemetry=tel,
            s_eval=4, bench_requests=64, max_batch=8,
            service_time_fn=_FAST,
        )
        tel.close()
        with ResultsStore(db) as store:
            rows = store.query_promotion_view()
        assert len(rows) == 1
        assert rows[0]["gate_events"] == 1
        assert rows[0]["gate_passes"] == 1
        assert rows[0]["last_phase"] == "gate"


# -- decision-cost attribution -------------------------------------------------


class TestDecisionCost:
    def test_orders_policies_by_waste_and_comfort(self):
        """The canary's comparator must separate thermostat-like serving
        from always-heat waste AND from don't-heat neglect."""
        cfg = _cfg()
        obs = synthetic_obs(256, A, seed=3)
        t = obs[..., 1]
        thermostat = np.where(t < 0, 1.0, 0.0).astype(np.float32)
        always = np.ones_like(thermostat)
        never = np.zeros_like(thermostat)
        c_thermo = decision_cost(cfg, obs, thermostat).mean()
        c_always = decision_cost(cfg, obs, always).mean()
        c_never = decision_cost(cfg, obs, never).mean()
        assert c_thermo < c_always
        assert c_thermo < c_never

    def test_trace_reward_mirrors_env_shape(self):
        cfg = _cfg()
        obs = synthetic_obs(16, A, seed=0)
        act = np.full((16, A), 0.5, dtype=np.float32)
        r = trace_reward(cfg, obs, act)
        assert r.shape == (16, A) and r.dtype == np.float32
        assert np.isfinite(r).all()


# -- trace export round trip ---------------------------------------------------


@pytest.fixture
def served_warehouse(crafted, tmp_path):
    """A gateway that served seeded traffic into a results DB; yields
    (cfg, db path, the obs that were sent, households, engine)."""
    cfg, dirs = crafted
    db = str(tmp_path / "wh.db")
    gateway = build_gateway(
        [dirs["incumbent"]], max_batch=8, max_wait_s=0.005,
        results_db=db, device="cpu",
        admission=AdmissionConfig(
            max_queue_depth=100_000, wait_budget_ms=1e9
        ),
        run_name="trace-test",
    )
    server = GatewayServer(gateway)
    host, port = server.start()
    obs = synthetic_obs(40, A, seed=11)
    households = [f"house-{i:02d}" for i in range(8)]
    traffic = _drive_wire_stage(host, port, obs, households)
    assert (traffic.statuses == 200).all()
    # Push the bundles' buffered warehouse rows NOW (the same mid-run
    # flush boundary the canary controller uses between stages).
    for h in gateway.registry.hashes:
        gateway.registry.get(h).telemetry.flush()
    engine = gateway.registry.get(gateway.registry.default_hash).engine
    yield cfg, db, obs, households, engine
    server.stop()


class TestTraceExport:
    def test_round_trip_shape_dtype_exact(self, served_warehouse):
        """Exported transitions are shape/dtype-exact against the live
        gateway's obs contract, and the obs round-trip the wire + the
        warehouse bit-exactly."""
        cfg, db, sent_obs, households, engine = served_warehouse
        ds = export_serve_traces(db, cfg=cfg)
        # One decision per request; one fewer transition per household.
        assert ds.n_decisions == sent_obs.shape[0]
        assert ds.n_transitions == sent_obs.shape[0] - len(households)
        # The serving contract: engine._check_obs accepts exactly this.
        assert ds.obs.shape == (ds.n_transitions, A, 4)
        assert ds.obs.dtype == np.float32
        assert ds.action.shape == (ds.n_transitions, A)
        assert ds.action.dtype == np.float32
        assert ds.reward.shape == (ds.n_transitions, A)
        assert ds.next_obs.shape == ds.obs.shape
        engine._check_obs(ds.obs)  # must not raise
        # Bit-exact wire/warehouse round trip: every exported obs row is
        # one of the sent rows, byte for byte.
        sent = {r.tobytes() for r in sent_obs}
        for row in ds.obs:
            assert row.tobytes() in sent
        # Transitions pair CONSECUTIVE decisions of one household: each
        # (obs, next_obs) pair must be the household's adjacent requests.
        idx_of = {r.tobytes(): i for i, r in enumerate(sent_obs)}
        for o, nxt in zip(ds.obs, ds.next_obs):
            i, j = idx_of[o.tobytes()], idx_of[nxt.tobytes()]
            assert (j - i) % len(households) == 0 and j > i

    def test_to_replay_state_ring_layout(self, served_warehouse):
        cfg, db, *_ = served_warehouse
        ds = export_serve_traces(db, cfg=cfg)
        rs = to_replay_state(ds)
        assert rs.obs.shape == (A, ds.n_transitions, 4)
        assert int(rs.count) == ds.n_transitions
        assert int(rs.cursor) == 0  # exactly full: cursor wrapped
        np.testing.assert_array_equal(
            np.asarray(rs.obs)[:, 0, :], ds.obs[0]
        )
        # Overflow keeps the NEWEST transitions.
        small = to_replay_state(ds, capacity=4)
        np.testing.assert_array_equal(
            np.asarray(small.obs), np.swapaxes(ds.obs[-4:], 0, 1)
        )

    def test_compacted_warehouse_fails_loud(self, served_warehouse):
        cfg, db, *_ = served_warehouse
        with ResultsStore(db) as store:
            out = store.compact_serve_telemetry(older_than_hours=0.0)
        assert out["decisions_compacted"] > 0
        with pytest.raises(TracesCompactedError, match="older-than-hours"):
            export_serve_traces(db, cfg=cfg)

    def test_anonymous_and_batch_rows_dropped_not_stitched(self, tmp_path):
        """Anonymous decisions (no household) and non-leading batch rows
        cannot honor the consecutive-slot pairing invariant; they must
        be DROPPED (counted), never stitched into fabricated
        transitions (review regression)."""
        cfg = _cfg()
        db = str(tmp_path / "wh.db")
        store = ResultsStore(db)
        store.con.execute(
            "INSERT INTO telemetry_runs VALUES "
            "(?,?,?,?,?,?,?,?,?,?,?,?)",
            ("run-1", None, "hash-1", None, None, None, None, None, None,
             None, None, json.dumps({"serve_role": "default"})),
        )
        obs = synthetic_obs(6, A, seed=0)

        def point(seq, household, row, o):
            attrs = {"obs": o.tolist(), "action": [0.0] * A, "row": row}
            if household is not None:
                attrs["household"] = household
            return ("run-1", seq, 1.0 + seq, "serve_decision", None, None,
                    json.dumps(attrs))

        rows = [
            point(0, "h1", 0, obs[0]),
            point(1, None, 0, obs[1]),   # anonymous: dropped
            point(2, "h1", 0, obs[2]),
            point(3, "h1", 1, obs[3]),   # batch row 1: dropped
            point(4, "h1", 0, obs[4]),
            point(5, None, 0, obs[5]),   # anonymous: dropped
        ]
        store.con.executemany(
            "INSERT INTO telemetry_points VALUES (?,?,?,?,?,?,?)", rows
        )
        store.con.commit()
        store.close()
        ds = export_serve_traces(db, cfg=cfg)
        assert ds.n_decisions == 3 and ds.n_dropped == 3
        # h1's three ROW-0 decisions pair into exactly two transitions —
        # none involving the anonymous or batch-row observations.
        assert ds.n_transitions == 2
        np.testing.assert_array_equal(ds.obs[0], obs[0])
        np.testing.assert_array_equal(ds.next_obs[0], obs[2])
        np.testing.assert_array_equal(ds.obs[1], obs[2])
        np.testing.assert_array_equal(ds.next_obs[1], obs[4])

    def test_empty_warehouse_fails_loud(self, tmp_path):
        db = str(tmp_path / "empty.db")
        ResultsStore(db).close()
        with pytest.raises(ValueError, match="no serve-role"):
            export_serve_traces(db, cfg=_cfg())


# -- continual training --------------------------------------------------------


def _fake_dataset(n=24, seed=0):
    rng = np.random.default_rng(seed)
    obs = np.empty((n, A, 4), np.float32)
    obs[..., 0] = rng.uniform(0, 1, (n, A))
    obs[..., 1:] = rng.uniform(-1, 1, (n, A, 3))
    act = rng.choice([0.0, 0.5, 1.0], (n, A)).astype(np.float32)
    rew = rng.normal(0, 1, (n, A)).astype(np.float32)
    return TraceDataset(
        obs=obs, action=act, reward=rew,
        next_obs=np.roll(obs, -1, axis=0),
    )


class TestContinual:
    def test_state_from_bundle_grafts_greedy_subtree(self, crafted):
        from p2pmicrogrid_tpu.serve.export import load_policy_bundle
        from p2pmicrogrid_tpu.train.continual import state_from_bundle

        cfg, dirs = crafted
        manifest, params = load_policy_bundle(dirs["good"])
        ps = state_from_bundle(cfg, manifest, params, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(
            np.asarray(ps.q_table), params["q_table"]
        )

    def test_dqn_graft_copies_target_and_trains_finite(self, tmp_path):
        from p2pmicrogrid_tpu.serve.export import (
            export_policy_bundle,
            load_policy_bundle,
        )
        from p2pmicrogrid_tpu.train import init_policy_state
        from p2pmicrogrid_tpu.train.continual import (
            offpolicy_pretrain,
            state_from_bundle,
        )

        cfg = _cfg(impl="dqn")
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "dqn-bundle"))
        manifest, params = load_policy_bundle(bundle)
        st = state_from_bundle(cfg, manifest, params, jax.random.PRNGKey(1))
        # Fine-tuning must not bootstrap against a random target.
        for o, t in zip(
            jax.tree_util.tree_leaves(st.online),
            jax.tree_util.tree_leaves(st.target),
        ):
            np.testing.assert_array_equal(np.asarray(o), np.asarray(t))
        st2, losses = offpolicy_pretrain(
            cfg, st, _fake_dataset(), jax.random.PRNGKey(2), steps=4
        )
        assert losses.shape == (4,) and np.isfinite(losses).all()
        moved = any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(
                jax.tree_util.tree_leaves(st.online),
                jax.tree_util.tree_leaves(st2.online),
            )
        )
        assert moved

    def test_train_continual_emits_distinct_candidate(self, crafted, tmp_path):
        from p2pmicrogrid_tpu.serve.export import load_policy_bundle
        from p2pmicrogrid_tpu.train.continual import train_continual

        cfg, dirs = crafted
        out = str(tmp_path / "candidate")
        result = train_continual(
            cfg, dirs["incumbent"], _fake_dataset(), out,
            str(tmp_path / "ckpt"), n_episodes=0, trace_steps=8,
        )
        manifest, _ = load_policy_bundle(out)
        assert manifest["config_hash"] == result.candidate_hash
        assert result.candidate_hash != result.incumbent_hash
        assert manifest["source"]["kind"] == "continual"
        assert manifest["source"]["incumbent"] == result.incumbent_hash
        assert result.trace_steps == 8

    def test_impl_mismatch_refused(self, crafted):
        from p2pmicrogrid_tpu.serve.export import load_policy_bundle
        from p2pmicrogrid_tpu.train.continual import state_from_bundle

        cfg, dirs = crafted
        manifest, params = load_policy_bundle(dirs["incumbent"])
        dqn_cfg = _cfg(impl="dqn")
        with pytest.raises(ValueError, match="SAME policy class"):
            state_from_bundle(dqn_cfg, manifest, params, jax.random.PRNGKey(0))


# -- canary --------------------------------------------------------------------


class TestCanary:
    def test_abort_restores_incumbent_zero_failed(self, crafted, tmp_path):
        """The headline rail: a regressed candidate forced past the gate
        is rolled back mid-canary under live traffic — zero failed
        requests, incumbent default restored, split AND pins cleared,
        post-rollback serving bit-exact to the incumbent."""
        cfg, dirs = crafted
        fields = run_promotion_pipeline(
            cfg, dirs["cost_regressed"], dirs["incumbent"],
            stages=(25.0, 100.0),
            results_db=str(tmp_path / "wh.db"),
            seed=5, requests_per_stage=96, n_households=64,
            skip_gate=True, max_batch=8,
        )
        assert fields["rolled_back"] and not fields["promoted"]
        assert fields["aborted_stage"] == 0
        assert fields["availability"] == 1.0
        assert fields["n_failed"] == 0
        assert fields["bit_exact_after"] is True
        assert any(
            "decision cost" in r for r in fields["abort_reasons"]
        )

    def test_good_candidate_promotes_end_to_end(self, crafted, tmp_path):
        cfg, dirs = crafted
        fields = run_promotion_pipeline(
            cfg, dirs["good"], dirs["incumbent"],
            stages=(25.0, 100.0),
            results_db=str(tmp_path / "wh.db"),
            seed=6, requests_per_stage=96, n_households=64,
            max_batch=8,
            gate_budgets=GateBudgets(),
            canary_budgets=CanaryBudgets(),
            gate_service_time_fn=_FAST,
        )
        assert fields["promoted"] and not fields["rolled_back"]
        assert fields["gate_verdict"] == "pass"
        assert fields["availability"] == 1.0
        assert fields["bit_exact_after"] is True
        assert len(fields["canary_stages"]) == 2
        # The final stage compared against the carried incumbent
        # baseline (the incumbent serves nothing at 100%).
        last = fields["canary_stages"][-1]
        inc_arm = last["arms"][fields["incumbent"]]
        assert inc_arm.get("baseline_decisions", 0) > 0

    def test_erroring_candidate_arm_is_visible(self):
        """Error responses carry no config_hash; the controller must
        attribute them to the arm the household's split slot routes to —
        otherwise a fully-erroring candidate is invisible to its own
        error guard and promotes (review regression)."""
        from p2pmicrogrid_tpu.serve.promotion import (
            CanaryController,
            StagePlan,
            StageTraffic,
        )
        from p2pmicrogrid_tpu.serve.registry import (
            BundleRegistry,
            _household_slot,
        )

        controller = CanaryController(
            BundleRegistry(), "cand-hash", "inc-hash",
            budgets=CanaryBudgets(max_error_rate=0.0),
        )
        households = [f"house-{i:04d}" for i in range(64)]
        plan = StagePlan(index=0, percent=25.0, is_promote=False)
        in_arm = [h for h in households if _household_slot(h) < 25.0]
        assert in_arm  # the split has members at 25%
        statuses, hashes, acts, hh = [], [], [], []
        for h in households:
            hh.append(h)
            if _household_slot(h) < 25.0:
                statuses.append(500)   # the candidate errors EVERY request
                hashes.append(None)    # ...and error bodies carry no hash
                acts.append(None)
            else:
                statuses.append(200)
                hashes.append("inc-hash")
                acts.append([0.0])
        traffic = StageTraffic(
            statuses=np.asarray(statuses),
            latencies_ms=np.ones(len(households)),
            config_hashes=hashes,
            actions=acts,
            households=hh,
        )
        report = controller._evaluate_stage(plan, traffic, time.time())
        assert not report.ok
        assert any("error rate" in r for r in report.reasons)
        assert report.arms["cand-hash"]["errors"] == len(in_arm)

    def test_swap_fn_rollback_reverses_fleet_swap(self):
        """A fleet-wide swap_fn promotion never touches the local
        registry default; a post-swap abort must still swap the FLEET
        back (review regression)."""
        from p2pmicrogrid_tpu.serve.promotion import (
            CanaryController,
            StageTraffic,
        )

        class FleetFrontRegistry:
            """The local view of a fleet front: the default stays the
            incumbent no matter what swap_fn pushes to the replicas."""

            def __init__(self):
                self.default_hash = "inc-hash"
                self.split = None

            def set_split(self, h, pct):
                self.split = (h, pct)

            def clear_split(self):
                self.split = None

            def clear_pins(self):
                pass

        swaps: list = []
        controller = CanaryController(
            FleetFrontRegistry(), "cand-hash", "inc-hash",
            stages=(100.0,),
            budgets=CanaryBudgets(max_error_rate=0.0),
            swap_fn=swaps.append,
        )

        def drive(plan):
            # The promote stage regresses: every request 500s.
            return StageTraffic(
                statuses=np.full(8, 500, dtype=np.int64),
                latencies_ms=np.ones(8),
                config_hashes=[None] * 8,
                actions=[None] * 8,
                households=[f"house-{i}" for i in range(8)],
            )

        result = controller.run(drive)
        assert result.rolled_back and not result.promoted
        # The fleet was swapped TO the candidate, then BACK.
        assert swaps == ["cand-hash", "inc-hash"]

    def test_controller_stage_validation(self, crafted):
        from p2pmicrogrid_tpu.serve.promotion import CanaryController
        from p2pmicrogrid_tpu.serve.registry import BundleRegistry

        with pytest.raises(ValueError, match="end at 100"):
            CanaryController(
                BundleRegistry(), "cand", "inc", stages=(5.0, 25.0)
            )
        with pytest.raises(ValueError, match="strictly increasing"):
            CanaryController(
                BundleRegistry(), "cand", "inc", stages=(25.0, 5.0, 100.0)
            )

    def test_registry_clear_pins(self, crafted):
        """clear_pins re-rolls routing so a widened split actually grows
        (the ramp-freeze regression the canary fix covers)."""
        from p2pmicrogrid_tpu.serve.engine import MicroBatchQueue
        from p2pmicrogrid_tpu.serve.registry import BundleRegistry

        from p2pmicrogrid_tpu.serve.export import load_policy_bundle

        cfg, dirs = crafted
        registry = BundleRegistry()
        for d in (dirs["incumbent"], dirs["good"]):
            engine = PolicyEngine(bundle_dir=d, max_batch=8, device="cpu")
            registry.register(engine, MicroBatchQueue(engine))
        cand = load_policy_bundle(dirs["good"])[0]["config_hash"]
        households = [f"house-{i:04d}" for i in range(128)]
        registry.set_split(cand, 5.0)
        at5 = sum(
            1 for h in households
            if registry.route(h).config_hash == cand
        )
        # WITHOUT clear_pins the widened split serves the 5% population.
        registry.set_split(cand, 50.0)
        frozen = sum(
            1 for h in households
            if registry.route(h).config_hash == cand
        )
        assert frozen == at5
        registry.clear_pins()
        registry.set_split(cand, 50.0)
        at50 = sum(
            1 for h in households
            if registry.route(h).config_hash == cand
        )
        assert at50 > at5
        registry.close_all()


# -- token rotation ------------------------------------------------------------


class TestTokenRotation:
    def test_mid_rotation_both_secrets_verify(self, tmp_path):
        path = str(tmp_path / "secret")
        old = serve_auth.generate_secret(path)
        old_token = serve_auth.mint_token(old, "house-1")
        new = serve_auth.rotate_secret(path, grace_s=60.0)
        assert new != old
        auth = serve_auth.TokenAuthenticator.from_secret_file(path)
        # Requests signed with EITHER secret pass mid-rotation.
        assert auth.check(old_token, "house-1")["household"] == "house-1"
        new_token = auth.mint("house-1")
        assert auth.check(new_token, "house-1")["household"] == "house-1"
        # Minting uses the NEW primary.
        with pytest.raises(serve_auth.AuthError):
            serve_auth.verify_token(old, new_token)

    def test_post_grace_old_secret_401(self, tmp_path):
        path = str(tmp_path / "secret")
        old = serve_auth.generate_secret(path)
        old_token = serve_auth.mint_token(old, "house-1")
        new = serve_auth.rotate_secret(path, grace_s=60.0)
        # Expiry is honored AT VERIFICATION TIME: build the chain with an
        # already-expired grace (a long-lived process past the window).
        auth = serve_auth.TokenAuthenticator(
            [(new, None), (old, time.time() - 1.0)]
        )
        with pytest.raises(serve_auth.AuthError) as err:
            auth.check(old_token, "house-1")
        assert err.value.status == 401
        # The new primary keeps verifying normally past the grace.
        token = auth.mint("house-1")
        assert auth.check(token, "house-1")["household"] == "house-1"

    def test_load_secret_chain_drops_expired(self, tmp_path):
        path = str(tmp_path / "secret")
        serve_auth.generate_secret(path)
        serve_auth.rotate_secret(path, grace_s=0.0)
        time.sleep(0.01)
        chain = serve_auth.load_secret_chain(path)
        assert len(chain) == 1  # expired .prev contributes nothing

    def test_cli_rotate(self, tmp_path, capsys):
        from p2pmicrogrid_tpu.cli import main

        path = str(tmp_path / "secret")
        assert main(["serve-token", "--new-secret", path]) == 0
        old = serve_auth.load_secret(path)
        old_token = serve_auth.mint_token(old, "house-7")
        assert main([
            "serve-token", "--rotate", "--secret-file", path,
            "--grace-s", "60",
        ]) == 0
        assert serve_auth.load_secret(path) != old
        # --verify checks the dual-secret chain: the pre-rotation token
        # still validates inside the grace.
        assert main([
            "serve-token", "--secret-file", path, "--verify", old_token,
        ]) == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["valid"] is True and doc["household"] == "house-7"


# -- probes over the persistent mux wire ---------------------------------------


class TestProbeMux:
    @pytest.fixture
    def mux_fleet(self, crafted):
        from p2pmicrogrid_tpu.serve.router import LocalFleet

        cfg, dirs = crafted
        fleet = LocalFleet(
            [dirs["incumbent"]], n_replicas=2, mux=True, device="cpu",
            admission=AdmissionConfig(
                max_queue_depth=100_000, wait_budget_ms=1e9
            ),
        )
        fleet.start()
        yield fleet
        fleet.stop_all()

    def test_probe_reuses_one_connection_across_sweeps(self, mux_fleet):
        from p2pmicrogrid_tpu.serve.router import FleetRouter

        router = FleetRouter(mux_fleet.replicas, probe_timeout_s=2.0)
        for _ in range(3):
            assert all(router.probe_once().values())
        # THE satellite contract: no fresh handshake per replica per
        # sweep — one persistent connection each, opened once.
        assert {
            rid: conn.connects
            for rid, conn in router._probe_conns.items()
        } == {"replica-0": 1, "replica-1": 1}
        router.close_probe_conns()

    def test_half_open_connection_detected_unhealthy(self, mux_fleet):
        from p2pmicrogrid_tpu.serve.router import FleetRouter

        router = FleetRouter(
            mux_fleet.replicas, probe_timeout_s=2.0, fail_threshold=1,
            ok_threshold=1,
        )
        assert all(router.probe_once().values())
        mux_fleet.kill("replica-0")
        sweep = router.probe_once()
        assert sweep["replica-0"] is False and sweep["replica-1"] is True
        assert not router.is_healthy("replica-0")
        mux_fleet.restart("replica-0")
        assert router.probe_once()["replica-0"] is True
        assert router.is_healthy("replica-0")
        # The reconnect shows in the probe connection's counter.
        assert router._probe_conns["replica-0"].connects >= 2
        router.close_probe_conns()

    def test_http_fallback_without_mux(self, crafted):
        from p2pmicrogrid_tpu.serve.router import FleetRouter, LocalFleet

        cfg, dirs = crafted
        fleet = LocalFleet(
            [dirs["incumbent"]], n_replicas=1, mux=False, device="cpu"
        )
        fleet.start()
        try:
            router = FleetRouter(fleet.replicas, probe_timeout_s=2.0)
            assert router.probe_once() == {"replica-0": True}
            assert not router._probe_conns  # HTTP path: no mux probes
        finally:
            fleet.stop_all()

    def test_forced_mux_probe_without_listener_refused(self, crafted):
        from p2pmicrogrid_tpu.serve.router import FleetRouter, Replica

        with pytest.raises(ValueError, match="probe_transport='mux'"):
            FleetRouter(
                [Replica("r0", "127.0.0.1", 1)], probe_transport="mux"
            )


# -- artifacts schema ----------------------------------------------------------


class TestPromotionSchema:
    GOOD_ROW = {
        "metric": "promotion_case", "value": 1.0, "unit": "availability",
        "vs_baseline": 1.0, "case": "good", "gate_verdict": "pass",
        "canary_stages": [{"percent": 5.0, "ok": True}],
        "availability": 1.0, "rolled_back": False, "promoted": True,
    }

    def _check(self, tmp_path, rows):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools", "check_artifacts_schema.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        path = tmp_path / "PROMOTION_test.jsonl"
        path.write_text("".join(json.dumps(r) + "\n" for r in rows))
        problems: list = []
        mod.check_promotion_jsonl(str(path), problems)
        return problems

    def test_good_capture_passes(self, tmp_path):
        assert self._check(tmp_path, [self.GOOD_ROW]) == []

    def test_contract_violations_flagged(self, tmp_path):
        bad = dict(self.GOOD_ROW)
        bad.pop("gate_verdict")
        bad["availability"] = 2.0
        bad["rolled_back"] = "no"
        problems = self._check(tmp_path, [bad])
        assert any("gate_verdict" in p for p in problems)
        assert any("outside [0, 1]" in p for p in problems)
        assert any("rolled_back" in p for p in problems)

    def test_caseless_capture_flagged(self, tmp_path):
        row = {
            "metric": "promotion_bench", "value": 1.0, "unit": "cases_ok",
            "vs_baseline": 1.0,
        }
        problems = self._check(tmp_path, [row])
        assert any("no promotion_case" in p for p in problems)

    def test_committed_capture_validates(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        capture = os.path.join(repo, "artifacts", "PROMOTION_r10.jsonl")
        assert os.path.exists(capture), "PROMOTION_r10.jsonl must be committed"
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(repo, "tools", "check_artifacts_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        problems = []
        mod.check_promotion_jsonl(capture, problems)
        assert problems == []
        rows = [json.loads(l) for l in open(capture)]
        headline = rows[-1]
        assert headline["metric"] == "promotion_bench"
        assert headline["all_safe"] is True
        cases = {r["case"]: r for r in rows if r["metric"] == "promotion_case"}
        assert cases["good"]["promoted"] is True
        assert cases["cost_regressed"]["blocked_at_gate"] is True
        assert cases["cost_regressed_forced"]["rolled_back"] is True
        assert cases["cost_regressed_forced"]["availability"] == 1.0
        assert cases["cost_regressed_forced"]["bit_exact_after"] is True
        assert cases["nan_poisoned"]["blocked_at_gate"] is True
        assert cases["slo_violating"]["blocked_at_gate"] is True


# -- CLI -----------------------------------------------------------------------


class TestPromotionCli:
    def test_promote_gate_only(self, crafted, tmp_path, capfd):
        from p2pmicrogrid_tpu.cli import main

        cfg, dirs = crafted
        rc = main([
            "promote", "--agents", str(A), "--implementation", "tabular",
            "--seed", "0", "--gate-only",
            "--candidate", dirs["cost_regressed"],
            "--incumbent", dirs["incumbent"],
        ])
        assert rc == 1  # gate refused the regressed candidate
        # capfd, not capsys: the guarded stdout sink emits at the fd level.
        out = capfd.readouterr().out.strip().splitlines()
        row = json.loads(out[-1])
        assert row["metric"] == "promotion_gate"
        assert "regresses" in row["gate_verdict"]

    @pytest.mark.slow
    def test_continual_cli_end_to_end(self, crafted, tmp_path, capfd):
        """Gateway traffic -> warehouse -> continual -> candidate bundle
        through the real CLI."""
        from p2pmicrogrid_tpu.cli import main

        cfg, dirs = crafted
        db = str(tmp_path / "wh.db")
        gateway = build_gateway(
            [dirs["incumbent"]], max_batch=8, results_db=db, device="cpu",
            admission=AdmissionConfig(
                max_queue_depth=100_000, wait_budget_ms=1e9
            ),
        )
        server = GatewayServer(gateway)
        host, port = server.start()
        obs = synthetic_obs(30, A, seed=2)
        _drive_wire_stage(
            host, port, obs, [f"house-{i}" for i in range(5)]
        )
        server.stop()
        out_dir = str(tmp_path / "candidate")
        rc = main([
            "continual", "--agents", str(A), "--implementation", "tabular",
            "--seed", "0", "--results-db", db,
            "--bundle", dirs["incumbent"], "--out", out_dir,
            "--episodes", "0", "--trace-steps", "5",
            "--model-dir", str(tmp_path / "models"),
        ])
        assert rc == 0
        assert os.path.exists(os.path.join(out_dir, "manifest.json"))
        rows = [
            json.loads(l)
            for l in capfd.readouterr().out.strip().splitlines()
            if l.startswith("{")
        ]
        result = [r for r in rows if r.get("metric") == "continual_result"]
        assert result and result[0]["trace_steps"] == 5
