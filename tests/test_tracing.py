"""Fleet-wide distributed tracing (ISSUE 16).

Acceptance for the tracing tier: deterministic trace/span ids from the
loadgen seed, a tolerant wire encoding shared by the ``x-p2p-trace``
header and the mux frame's ``trace`` field (with ``MuxPool`` replays
bumping the hop counter), spans landing in the warehouse's
``trace_spans`` table and stitching back into cross-process trees, an
additive critical-path decomposition whose segments sum to the root
span's measured wall time, and — slow tier — one SIGKILL chaos run whose
victim's requests reconstruct as a SINGLE tree spanning >= 3 processes
including the failover hop. Tracing off must stay off: no ``--trace``,
no ``trace_span`` rows.
"""

import asyncio
import itertools
import json
import sqlite3
import time

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.serve import export_policy_bundle
from p2pmicrogrid_tpu.serve.wire import MuxPool, serve_mux_connection
from p2pmicrogrid_tpu.telemetry.report import (
    aggregate_critical_paths,
    chrome_trace_export,
    render_trace_tree,
    trace_critical_path,
)
from p2pmicrogrid_tpu.telemetry.tracing import (
    TRACE_HEADER,
    TraceContext,
    bump_hop,
    decode,
    new_span_id,
    record_span,
    root_context,
)
from p2pmicrogrid_tpu.train import init_policy_state

A = 3


def _make_bundle(tmp_path, seed, name):
    cfg = default_config(
        sim=SimConfig(n_agents=A),
        train=TrainConfig(implementation="tabular", seed=seed),
    )
    ps = init_policy_state(cfg, jax.random.PRNGKey(seed))
    ps = ps._replace(
        q_table=jax.random.normal(
            jax.random.PRNGKey(seed + 1), ps.q_table.shape
        )
    )
    return export_policy_bundle(cfg, ps, str(tmp_path / name))


class TestTraceContext:
    def test_root_context_deterministic(self):
        a = root_context(7, 3)
        assert a == root_context(7, 3)
        assert a.trace_id != root_context(7, 4).trace_id
        assert a.trace_id != root_context(8, 3).trace_id
        assert len(a.trace_id) == 32 and len(a.span_id) == 16
        assert a.parent_span_id is None and a.hop == 0

    def test_encode_decode_round_trip(self):
        ctx = root_context(0, 0).with_hop(2)
        back = decode(ctx.encode())
        assert back is not None
        assert (back.trace_id, back.span_id, back.hop) == (
            ctx.trace_id, ctx.span_id, 2
        )
        # The receiver does not know the sender's parent linkage.
        assert back.parent_span_id is None

    def test_child_is_deterministic_and_parented(self):
        ctx = root_context(1, 1)
        c1 = ctx.child("router.attempt0")
        assert c1 == ctx.child("router.attempt0")
        assert c1 != ctx.child("router.attempt1")
        assert c1.parent_span_id == ctx.span_id
        assert c1.trace_id == ctx.trace_id
        # Grandchild chains keep linking.
        g = c1.child("queue.wait")
        assert g.parent_span_id == c1.span_id

    def test_bump_hop(self):
        ctx = root_context(0, 0)
        bumped = decode(bump_hop(ctx.encode()))
        assert bumped.hop == ctx.hop + 1
        assert (bumped.trace_id, bumped.span_id) == (
            ctx.trace_id, ctx.span_id
        )
        # Malformed input passes through unchanged, never raises.
        assert bump_hop("not-a-trace") == "not-a-trace"

    @pytest.mark.parametrize("garbage", [
        None, 7, "", "a-b", "a-b-c-d", "x" * 32 + "-" + "y" * 16 + "-00",
        "0" * 31 + "-" + "0" * 16 + "-00",
    ])
    def test_decode_garbage_is_none(self, garbage):
        assert decode(garbage) is None

    def test_new_span_id_shape(self):
        ids = {new_span_id() for _ in range(32)}
        assert len(ids) == 32
        assert all(len(i) == 16 for i in ids)

    def test_record_span_is_noop_without_telemetry_or_context(self):
        record_span(None, root_context(0, 0), "x", 0.0, 0.0)
        record_span(object(), None, "x", 0.0, 0.0)  # would raise if used


class TestWarehouseTraceTree:
    def test_spans_round_trip_into_tree(self, tmp_path):
        from p2pmicrogrid_tpu.data import ResultsStore
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        db = str(tmp_path / "results.db")
        tel = Telemetry(run_id="trace-test", sinks=[SqliteSink(db)])
        root = root_context(3, 0)
        t0 = time.time()
        record_span(tel, root, "router.act", t0, 0.1, retries=0)
        att = root.child("router.attempt0")
        record_span(tel, att, "router.attempt", t0 + 0.001, 0.08,
                    replica_id="replica-0", status=200)
        record_span(tel, att.child("queue.wait"), "queue.wait",
                    t0 + 0.002, 0.01)
        tel.close()

        store = ResultsStore(db)
        try:
            spans = store.query_trace_tree(root.trace_id)
        finally:
            store.close()
        assert [s["name"] for s in spans] == [
            "router.act", "router.attempt", "queue.wait"
        ]
        by_id = {s["span_id"]: s for s in spans}
        assert spans[0]["parent_span_id"] is None
        assert by_id[att.span_id]["parent_span_id"] == root.span_id
        assert by_id[att.span_id]["attrs"]["replica_id"] == "replica-0"
        # Every span's process label landed (one Perfetto lane per process).
        assert all(s["process"] for s in spans)

    def test_histogram_exemplars_link_slowest_traces(self, tmp_path):
        from p2pmicrogrid_tpu.data import ResultsStore
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        db = str(tmp_path / "results.db")
        tel = Telemetry(run_id="exemplar-test", sinks=[SqliteSink(db)])
        for i, v in enumerate([2.0, 900.0, 40.0]):
            tel.histogram(
                "router.latency_ms", v,
                trace_id=root_context(0, i).trace_id,
            )
        tel.close()
        store = ResultsStore(db)
        try:
            rows = store.query_slowest_traces(2)
        finally:
            store.close()
        assert rows, "exemplars should surface"
        assert rows[0]["latency_ms"] == 900.0
        assert rows[0]["trace_id"] == root_context(0, 1).trace_id


class TestCriticalPath:
    def _failover_spans(self):
        """A synthetic failover tree: one failed attempt + backoff, a
        winning attempt with queue/execute children (half-padded lane),
        100 ms end to end."""
        root = root_context(5, 0)
        a0 = root.child("router.attempt0")
        bk = root.child("router.backoff0")
        a1 = root.child("router.attempt1")
        qw = a1.child("queue.wait")
        ex = a1.child("engine.execute")

        def span(ctx, name, ts, dur, process, **attrs):
            return {
                "trace_id": ctx.trace_id, "span_id": ctx.span_id,
                "parent_span_id": ctx.parent_span_id, "name": name,
                "ts": ts, "duration_s": dur, "process": process,
                "attrs": attrs,
            }

        return [
            span(root, "router.act", 0.0, 0.100, "router:1", retries=1),
            span(a0, "router.attempt", 0.0, 0.030, "router:1",
                 replica_id="replica-0", status=503),
            span(bk, "router.backoff", 0.030, 0.005, "router:1"),
            span(a1, "router.attempt", 0.035, 0.060, "router:1",
                 replica_id="replica-1", status=200, failover=True),
            span(qw, "queue.wait", 0.036, 0.010, "gateway:2"),
            span(ex, "engine.execute", 0.046, 0.020, "gateway:2",
                 bucket=8, padded_rows=4, batch_size=4),
        ]

    def test_segments_sum_to_total(self):
        cp = trace_critical_path(self._failover_spans())
        assert cp["root"] == "router.act"
        assert cp["total_ms"] == pytest.approx(100.0)
        # Failed attempt (30) + backoff (5).
        assert cp["retry_ms"] == pytest.approx(35.0)
        assert cp["queue_wait_ms"] == pytest.approx(10.0)
        # 20 ms execute, half the lanes padding.
        assert cp["padding_ms"] == pytest.approx(10.0)
        assert cp["execute_ms"] == pytest.approx(10.0)
        segments = (cp["wire_ms"] + cp["queue_wait_ms"] + cp["padding_ms"]
                    + cp["execute_ms"] + cp["retry_ms"])
        assert segments == pytest.approx(cp["total_ms"], rel=1e-6)
        assert cp["n_processes"] == 2

    def test_losing_attempts_queue_time_not_charged(self):
        """queue/execute under the FAILED attempt count as retry, not as
        queue-wait — only the winning path's spans decompose."""
        spans = self._failover_spans()
        a0_id = spans[1]["span_id"]
        spans.append({
            "trace_id": spans[0]["trace_id"], "span_id": "f" * 16,
            "parent_span_id": a0_id, "name": "queue.wait",
            "ts": 0.001, "duration_s": 0.025, "process": "gateway:3",
            "attrs": {},
        })
        cp = trace_critical_path(spans)
        assert cp["queue_wait_ms"] == pytest.approx(10.0)  # unchanged

    def test_aggregate_picks_percentile_exemplars(self):
        trees = []
        for i in range(10):
            root = root_context(9, i)
            trees.append([{
                "trace_id": root.trace_id, "span_id": root.span_id,
                "parent_span_id": None, "name": "router.act",
                "ts": 0.0, "duration_s": 0.01 * (i + 1),
                "process": "router:1", "attrs": {},
            }])
        agg = aggregate_critical_paths(trees)
        assert agg["n_traces"] == 10
        assert agg["p50"]["total_ms"] < agg["p95"]["total_ms"]
        assert agg["p99"]["total_ms"] == pytest.approx(100.0)

    def test_render_tree_text(self):
        text = render_trace_tree(self._failover_spans())
        assert "router.act" in text and "engine.execute" in text
        assert "2 process(es)" in text
        assert "replica_id=replica-1" in text
        # Children indent under their parents.
        lines = text.splitlines()
        act = next(l for l in lines if "router.act" in l)
        qw = next(l for l in lines if "queue.wait" in l)
        assert len(qw) - len(qw.lstrip()) > len(act) - len(act.lstrip())

    def test_chrome_trace_export_lanes(self):
        doc = chrome_trace_export(self._failover_spans())
        events = doc["traceEvents"]
        meta = [e for e in events if e["ph"] == "M"]
        complete = [e for e in events if e["ph"] == "X"]
        assert {m["args"]["name"] for m in meta} == {"router:1", "gateway:2"}
        assert len(complete) == 6
        assert min(e["ts"] for e in complete) == 0.0  # rebased
        assert doc["otherData"]["trace_id"] == self._failover_spans()[0][
            "trace_id"
        ]


class TestMuxTracePropagation:
    def test_trace_field_reaches_route_and_replay_bumps_hop(self):
        """One mux request through a server that drops the FIRST
        connection cold: the pool replays on a fresh connection and the
        route sees the SAME trace identity one hop later."""
        seen = []
        conn_no = itertools.count()

        async def route(method, path, body, token, trace=None):
            seen.append(trace)
            return 200, {"ok": True}, []

        async def handler(r, w):
            if next(conn_no) == 0:
                w.close()  # cold drop: client replays
                return
            try:
                await serve_mux_connection(r, w, route)
            finally:
                w.close()

        ctx = root_context(2, 0)

        async def run():
            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            pool = MuxPool("127.0.0.1", port, size=1)
            try:
                status, doc, _ = await pool.request(
                    "/v1/act", {"x": 1}, 5.0, trace=ctx.encode()
                )
            finally:
                await pool.close()
                server.close()
                await server.wait_closed()
            return status, pool.replays

        status, replays = asyncio.run(run())
        assert status == 200 and replays == 1
        assert len(seen) == 1
        delivered = decode(seen[0])
        assert (delivered.trace_id, delivered.span_id) == (
            ctx.trace_id, ctx.span_id
        )
        assert delivered.hop == 1  # the replay, not the original send

    def test_untraced_route_stub_keeps_working(self):
        """A deployed 4-arg route (no ``trace`` parameter) still serves
        traced frames — the wire upgrade never breaks a handler."""
        async def route(method, path, body, token):
            return 200, {"ok": True}, []

        async def handler(r, w):
            try:
                await serve_mux_connection(r, w, route)
            finally:
                w.close()

        async def run():
            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            port = server.sockets[0].getsockname()[1]
            pool = MuxPool("127.0.0.1", port, size=1)
            try:
                return await pool.request(
                    "/v1/act", {}, 5.0,
                    trace=root_context(0, 0).encode(),
                )
            finally:
                await pool.close()
                server.close()
                await server.wait_closed()

        status, doc, _ = asyncio.run(run())
        assert status == 200


class TestServeBenchTraceInProcess:
    """The fast (in-process LocalFleet) capture path: serve-bench --fleet
    --trace emits a stitched tree + an additive critical-path headline,
    ids are deterministic from --bench-seed, and WITHOUT --trace the
    warehouse stays span-free."""

    def test_trace_headline_and_tree(self, tmp_path, capfd):
        from p2pmicrogrid_tpu import cli

        bundle = _make_bundle(tmp_path, 0, "b1")
        db = str(tmp_path / "results.db")
        rc = cli.main([
            "serve-bench", "--fleet", "--trace",
            "--bundle", bundle, "--replicas", "2",
            "--requests", "32", "--rate", "64",
            "--bench-seed", "7",
            "--agents", str(A), "--results-db", db,
        ])
        assert rc == 0
        lines = [
            json.loads(l)
            for l in capfd.readouterr().out.splitlines()
            if l.strip().startswith("{")
        ]
        tree = next(r for r in lines if r.get("kind") == "trace_tree")
        headline = next(
            r for r in lines if r.get("metric") == "serve_bench_trace"
        )
        # The stitched tree is complete: every parent id resolves.
        assert tree["tree_complete"] is True
        assert tree["n_spans"] >= 5
        names = {s["name"] for s in tree["spans"]}
        assert {"router.act", "router.attempt", "gateway.act",
                "queue.wait", "engine.execute"} <= names
        # Deterministic ids: the exemplar trace is one of the seeded
        # roots, byte-identical across replays of this schedule.
        expected = {root_context(7, i).trace_id for i in range(32)}
        assert tree["trace_id"] in expected
        # Additive decomposition against the measured root latency.
        cp = headline["critical_path"]
        segments = (cp["wire_ms"] + cp["queue_wait_ms"] + cp["padding_ms"]
                    + cp["execute_ms"] + cp["retry_ms"])
        assert segments == pytest.approx(cp["total_ms"], rel=0.05)
        assert headline["critical_path_percentiles"]["n_traces"] == 32
        # The warehouse answers for every request traced.
        con = sqlite3.connect(db)
        try:
            n_traces = con.execute(
                "SELECT COUNT(DISTINCT trace_id) FROM trace_spans"
            ).fetchone()[0]
        finally:
            con.close()
        assert n_traces == 32

    def test_trace_off_means_no_spans(self, tmp_path, capfd):
        from p2pmicrogrid_tpu import cli

        bundle = _make_bundle(tmp_path, 0, "b1")
        db = str(tmp_path / "results.db")
        rc = cli.main([
            "serve-bench", "--fleet",
            "--bundle", bundle, "--replicas", "2",
            "--requests", "32", "--rate", "64",
            "--agents", str(A), "--results-db", db,
        ])
        assert rc == 0
        out = capfd.readouterr().out
        assert "serve_bench_trace" not in out
        con = sqlite3.connect(db)
        try:
            n = con.execute("SELECT COUNT(*) FROM trace_spans").fetchone()[0]
        finally:
            con.close()
        assert n == 0

    def test_telemetry_query_renders_tree(self, tmp_path, capfd):
        from p2pmicrogrid_tpu import cli
        from p2pmicrogrid_tpu.data import ResultsStore
        from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry

        db = str(tmp_path / "results.db")
        tel = Telemetry(run_id="q-test", sinks=[SqliteSink(db)])
        root = root_context(0, 0)
        t0 = time.time()
        record_span(tel, root, "router.act", t0, 0.05)
        record_span(tel, root.child("router.attempt0"), "router.attempt",
                    t0, 0.04, replica_id="replica-0", status=200)
        tel.histogram("router.latency_ms", 50.0, trace_id=root.trace_id)
        tel.close()

        rc = cli.main(["telemetry-query", "--results-db", db,
                       "--trace", root.trace_id])
        assert rc == 0
        out = capfd.readouterr().out
        assert "router.act" in out and "critical_path" in out

        rc = cli.main(["telemetry-query", "--results-db", db,
                       "--slowest", "1"])
        assert rc == 0
        rows = [json.loads(l)
                for l in capfd.readouterr().out.splitlines()
                if l.strip().startswith("{")]
        assert rows and rows[0]["trace_id"] == root.trace_id

        # Satellite: the merged Perfetto export over the same warehouse.
        out_path = tmp_path / "trace.json"
        rc = cli.main(["telemetry-report", "--perfetto", root.trace_id,
                       "--trace-db", db, "--out", str(out_path)])
        assert rc == 0
        doc = json.loads(out_path.read_text())
        assert any(e.get("ph") == "X" for e in doc["traceEvents"])


@pytest.mark.slow
class TestProcessChaosTraceEndToEnd:
    def test_sigkilled_replica_requests_stitch_across_processes(
        self, tmp_path, capfd
    ):
        """The TRACE_r16 capture path end to end: real subprocess
        replicas, one SIGKILLed mid-run, --trace on — at least one
        request reconstructs as a SINGLE tree spanning >= 3 processes
        (router + two replicas via the failover hop), and the p99
        critical-path segments sum to the measured latency within 5%."""
        from p2pmicrogrid_tpu import cli

        bundle = _make_bundle(tmp_path, 0, "b1")
        db = str(tmp_path / "results.db")
        rc = cli.main([
            "serve-bench", "--fleet", "--process", "--chaos", "--trace",
            "--bundle", bundle,
            "--replicas", "2",
            "--requests", "192", "--rate", "64",
            # The kill must land AFTER the trace-stall window drains
            # (stall [0.3, 0.6) + 0.8 s hold -> victim-side spans flush
            # by ~1.4 s): an earlier SIGKILL loses the victim's half of
            # the failover trees this capture exists to stitch.
            "--kill-at", "1.8", "--restart-at", "3.5",
            "--bench-seed", "0",
            "--agents", str(A), "--results-db", db,
        ])
        assert rc == 0
        lines = [
            json.loads(l)
            for l in capfd.readouterr().out.splitlines()
            if l.strip().startswith("{")
        ]
        tree = next(r for r in lines if r.get("kind") == "trace_tree")
        headline = next(
            r for r in lines if r.get("metric") == "serve_bench_trace"
        )
        assert headline["tree_complete"] is True
        assert headline["n_processes"] >= 3
        assert headline["failover"] is True
        # The failover hop is IN the tree: two distinct replica_ids
        # under one root.
        assert tree["trace_id"] == headline["trace_id"]
        cp = headline["critical_path"]
        segments = (cp["wire_ms"] + cp["queue_wait_ms"] + cp["padding_ms"]
                    + cp["execute_ms"] + cp["retry_ms"])
        assert segments == pytest.approx(cp["total_ms"], rel=0.05)
        assert headline["measured_ms"] == pytest.approx(
            cp["total_ms"], rel=0.05
        )
        # Deterministic roots under the fixed seed.
        expected = {root_context(0, i).trace_id for i in range(192)}
        assert tree["trace_id"] in expected
        # The tree reconstructs from the warehouse too, not just the
        # capture: telemetry-query --trace renders it.
        rc = cli.main(["telemetry-query", "--results-db", db,
                       "--trace", tree["trace_id"]])
        assert rc == 0
        out = capfd.readouterr().out
        assert "router.attempt" in out
