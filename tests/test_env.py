"""Community-environment tests.

The load-bearing test is the negotiation equivalence: the vmapped/scanned
negotiation + clearing is replayed against a sequential NumPy re-derivation of
the reference's per-agent loop (community.py:67-93, agent.py:186-213) with a
planted greedy Q-table, slot by slot.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    QLearningConfig,
    SimConfig,
    TrainConfig,
    DQNConfig,
    DDPGConfig,
    default_config,
)
from p2pmicrogrid_tpu.data import synthetic_traces
from p2pmicrogrid_tpu.envs import (
    build_episode_arrays,
    init_physical,
    make_ratings,
    rule_baseline_episode,
    run_episode,
)
from p2pmicrogrid_tpu.models import tabular_init
from p2pmicrogrid_tpu.train import (
    evaluate_community,
    init_policy_state,
    make_policy,
    train_community,
)


def small_cfg(impl="tabular", **sim_kw):
    sim = SimConfig(n_agents=2, **sim_kw)
    return default_config(
        sim=sim,
        train=TrainConfig(
            max_episodes=2, min_episodes_criterion=1, implementation=impl
        ),
        dqn=DQNConfig(buffer_size=200, warmup_passes=1),
        ddpg=DDPGConfig(buffer_size=200, batch_size=16),
    )


@pytest.fixture(scope="module")
def day_traces():
    return synthetic_traces(n_days=1, start_day=11).normalized()


class TestRuleBaseline:
    def test_comfort_band_held(self, day_traces):
        cfg = small_cfg()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        phys = init_physical(cfg, jax.random.PRNGKey(0))
        _, out = rule_baseline_episode(cfg, phys, arrays)
        # Bang-bang with 15-min steps overshoots slightly but stays near band.
        assert float(out.t_in.min()) > 18.5
        assert float(out.t_in.max()) < 23.5
        assert out.cost.shape == (96, 2)

    def test_no_p2p_power(self, day_traces):
        cfg = small_cfg()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        phys = init_physical(cfg, jax.random.PRNGKey(0))
        _, out = rule_baseline_episode(cfg, phys, arrays)
        np.testing.assert_allclose(np.asarray(out.p_p2p), 0.0)


class TestEpisode:
    def test_shapes_and_determinism(self, day_traces):
        cfg = small_cfg()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        phys = init_physical(cfg, jax.random.PRNGKey(0))

        run = jax.jit(
            lambda ps, ph, k: run_episode(
                cfg, policy, ps, ph, arrays, ratings, k, training=True
            )
        )
        _, ps1, out1 = run(ps, phys, jax.random.PRNGKey(7))
        _, ps2, out2 = run(ps, phys, jax.random.PRNGKey(7))

        assert out1.reward.shape == (96, 2)
        assert out1.decisions.shape == (96, cfg.sim.rounds + 1, 2)
        np.testing.assert_array_equal(np.asarray(out1.reward), np.asarray(out2.reward))
        np.testing.assert_array_equal(
            np.asarray(ps1.q_table), np.asarray(ps2.q_table)
        )

    def test_learning_changes_qtable(self, day_traces):
        cfg = small_cfg()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        phys = init_physical(cfg, jax.random.PRNGKey(0))
        _, ps2, _ = run_episode(
            cfg, policy, ps, phys, arrays, ratings, jax.random.PRNGKey(7), training=True
        )
        assert float(jnp.abs(ps2.q_table - ps.q_table).max()) > 0.0

    def test_eval_does_not_learn(self, day_traces):
        cfg = small_cfg()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        phys = init_physical(cfg, jax.random.PRNGKey(0))
        _, ps2, _ = run_episode(
            cfg, policy, ps, phys, arrays, ratings, jax.random.PRNGKey(7), training=False
        )
        np.testing.assert_array_equal(np.asarray(ps.q_table), np.asarray(ps2.q_table))

    def test_power_balance_conservation(self, day_traces):
        """Matched P2P power sums to zero across the community: what one agent
        buys peer-to-peer another sold (clear_market antisymmetry)."""
        cfg = small_cfg()
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        # Plant a random table so actions/powers are non-trivial.
        ps = ps._replace(
            q_table=jax.random.normal(jax.random.PRNGKey(5), ps.q_table.shape)
        )
        phys = init_physical(cfg, jax.random.PRNGKey(0))
        _, _, out = run_episode(
            cfg, policy, ps, phys, arrays, ratings, jax.random.PRNGKey(7), training=False
        )
        np.testing.assert_allclose(
            np.asarray(out.p_p2p.sum(axis=-1)), 0.0, atol=1e-3
        )


@pytest.mark.slow
class TestNegotiationEquivalence:
    """Vectorized negotiation vs a sequential NumPy replay of the reference's
    agent loop (community.py:75-93, agent.py:178-213, rl.py:89-117 greedy)."""

    def _numpy_reference_slot(self, cfg, qcfg, q_table, ratings, phys_tin, time_norm,
                              balance_w, rounds):
        A = balance_w.shape[0]
        hp_max = cfg.thermal.hp_max_power
        setp, marg = cfg.thermal.setpoint, cfg.thermal.margin
        actions = np.array([0.0, 0.5, 1.0])
        hp_frac = np.zeros(A)
        p2p = np.zeros((A, A))

        def discretize(obs):
            t = int(np.clip(int(obs[0] * qcfg.num_time_states), 0, qcfg.num_time_states - 1))
            tp = int(np.clip(int((obs[1] + 1) / 2 * (qcfg.num_temp_states - 2) + 1), 0, qcfg.num_temp_states - 1))
            b = int(np.clip(int((obs[2] + 1) / 2 * qcfg.num_balance_states), 0, qcfg.num_balance_states - 1))
            p = int(np.clip(int((obs[3] + 1) / 2 * qcfg.num_p2p_states), 0, qcfg.num_p2p_states - 1))
            return t, tp, b, p

        for r in range(rounds + 1):
            np.fill_diagonal(p2p, 0.0)
            new_rows = np.zeros((A, A))
            for i in range(A):
                powers = -p2p[:, i]
                p2p_mean = powers.mean() / ratings.max_in[i]
                norm_temp = (phys_tin[i] - setp) / marg
                obs = np.array([time_norm, norm_temp, balance_w[i] / ratings.max_in[i], p2p_mean])
                ti, tpi, bi, pi = discretize(obs)
                a = int(np.argmax(q_table[i, ti, tpi, bi, pi]))
                hp_frac[i] = actions[a]
                out = balance_w[i] + hp_frac[i] * hp_max
                filtered = np.where(np.sign(out) != np.sign(powers), powers, 0.0)
                total = abs(filtered.sum())
                if total == 0.0:
                    p_out = out * np.ones(A) / A
                else:
                    p_out = out * np.abs(filtered) / total
                new_rows[i] = p_out
            p2p = new_rows

        p2p_t = p2p.T
        p_match = np.where(np.sign(p2p) != np.sign(p2p_t), p2p, 0.0)
        exchange = np.sign(p_match) * np.minimum(np.abs(p_match), np.abs(p_match).T)
        p_grid = (p2p - exchange).sum(axis=1)
        p_p2p = exchange.sum(axis=1)
        return p_grid, p_p2p, hp_frac

    @pytest.mark.parametrize("rounds", [0, 1, 2])
    @pytest.mark.parametrize("n_agents", [2, 3, 5])
    def test_matches_sequential_reference(self, day_traces, rounds, n_agents):
        cfg = small_cfg(rounds=rounds)
        cfg = cfg.replace(sim=SimConfig(n_agents=n_agents, rounds=rounds))
        qcfg = cfg.qlearning
        rng = np.random.default_rng(3)
        ratings = make_ratings(cfg, rng)
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        policy = make_policy(cfg)

        ps = tabular_init(qcfg, n_agents)
        ps = ps._replace(
            q_table=jax.random.normal(jax.random.PRNGKey(5), ps.q_table.shape)
        )
        phys = init_physical(cfg, jax.random.PRNGKey(0))

        _, _, out = run_episode(
            cfg, policy, ps, phys, arrays, ratings, jax.random.PRNGKey(7),
            training=False,
        )

        # Replay slots 0..4 sequentially; thermal state must be advanced the
        # same way between slots.
        q_np = np.asarray(ps.q_table)
        t_in = np.asarray(phys.t_in).copy()
        t_bm = np.asarray(phys.t_bm).copy()
        from p2pmicrogrid_tpu.ops.thermal import thermal_step

        for t in range(5):
            balance_w = np.asarray(arrays.load_w[t] - arrays.pv_w[t])
            p_grid, p_p2p, hp_frac = self._numpy_reference_slot(
                cfg, qcfg, q_np, ratings, t_in, float(arrays.time[t]), balance_w,
                rounds,
            )
            np.testing.assert_allclose(
                np.asarray(out.p_grid[t]), p_grid, rtol=1e-4, atol=1e-2
            )
            np.testing.assert_allclose(
                np.asarray(out.p_p2p[t]), p_p2p, rtol=1e-4, atol=1e-2
            )
            t_in_new, t_bm_new = thermal_step(
                cfg.thermal,
                cfg.sim.dt_seconds,
                jnp.asarray(arrays.t_out[t]),
                jnp.asarray(t_in),
                jnp.asarray(t_bm),
                jnp.asarray(hp_frac * cfg.thermal.hp_max_power),
            )
            t_in, t_bm = np.asarray(t_in_new), np.asarray(t_bm_new)


@pytest.mark.slow
class TestTraining:
    @pytest.mark.parametrize("impl", ["tabular", "dqn", "ddpg"])
    def test_two_episodes_run(self, day_traces, impl):
        cfg = small_cfg(impl)
        rng = np.random.default_rng(42)
        ratings = make_ratings(cfg, rng)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        res = train_community(cfg, policy, ps, day_traces, ratings, jax.random.PRNGKey(0))
        assert len(res.episode_rewards) == 2
        assert all(np.isfinite(r) for r in res.episode_rewards)
        assert res.env_steps == 2 * 96
        assert res.progress  # decay/progress hook fired at episode 0

    def test_jit_block_fusion_equivalent_count(self, day_traces):
        cfg = small_cfg()
        cfg = cfg.replace(
            train=TrainConfig(
                max_episodes=4, min_episodes_criterion=2, episodes_per_jit_block=2
            )
        )
        rng = np.random.default_rng(42)
        ratings = make_ratings(cfg, rng)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        res = train_community(cfg, policy, ps, day_traces, ratings, jax.random.PRNGKey(0))
        assert len(res.episode_rewards) == 4

    def test_non_multiple_block_clamps_to_max_episodes(self, day_traces):
        # 5 episodes with block 2: the final block must be clamped to 1, not
        # run a full extra block past max_episodes (ADVICE round 1).
        cfg = small_cfg()
        cfg = cfg.replace(
            train=TrainConfig(
                max_episodes=5, min_episodes_criterion=2, episodes_per_jit_block=2
            )
        )
        rng = np.random.default_rng(42)
        ratings = make_ratings(cfg, rng)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        res = train_community(cfg, policy, ps, day_traces, ratings, jax.random.PRNGKey(0))
        assert len(res.episode_rewards) == 5
        assert res.env_steps == 5 * 96


class TestEvaluation:
    def test_per_day_eval_shapes(self):
        traces = synthetic_traces(n_days=3, start_day=8).normalized()
        cfg = small_cfg()
        rng = np.random.default_rng(42)
        ratings = make_ratings(cfg, rng)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        days, out, day_arrays = evaluate_community(
            cfg, policy, ps, traces, ratings, jax.random.PRNGKey(0), rng=rng
        )
        assert days.tolist() == [8, 9, 10]
        assert out.cost.shape == (3, 96, 2)
        assert np.isfinite(np.asarray(out.cost)).all()
        assert day_arrays.load_w.shape == (3, 96, 2)
