"""Serve gateway: network front + hot-swap registry + admission control.

Tier-1 acceptance for ISSUE 5: a real socket server over the microbatch
queue serves concurrent households bit-identically to a direct
``PolicyEngine.act``, hot-swaps bundles mid-traffic with zero failed
requests, sheds load with 429 under forced saturation, drains before
close, and the wire-level serve-bench lands per-request traces in the
SQLite warehouse keyed by the SERVING bundle's config_hash. Fast and
JAX_PLATFORMS=cpu-safe by design.
"""

import http.client
import json
import threading
import time

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.serve import (
    AdmissionConfig,
    BundleRegistry,
    GatewayServer,
    MicroBatchQueue,
    PolicyEngine,
    build_gateway,
    export_policy_bundle,
    serve_bench_network,
)
from p2pmicrogrid_tpu.train import init_policy_state

A = 3  # community size for all gateway tests


def _make_bundle(tmp_path, seed, name):
    """A tabular bundle with non-trivial greedy structure; distinct seeds
    give distinct config_hashes (the registry key)."""
    cfg = default_config(
        sim=SimConfig(n_agents=A),
        train=TrainConfig(implementation="tabular", seed=seed),
    )
    ps = init_policy_state(cfg, jax.random.PRNGKey(seed))
    ps = ps._replace(
        q_table=jax.random.normal(
            jax.random.PRNGKey(seed + 1), ps.q_table.shape
        )
    )
    return export_policy_bundle(cfg, ps, str(tmp_path / name))


def _obs(n, seed=0):
    rng = np.random.default_rng(seed)
    obs = np.empty((n, A, 4), dtype=np.float32)
    obs[..., 0] = rng.uniform(0, 1, (n, A))
    obs[..., 1:] = rng.uniform(-1, 1, (n, A, 3))
    return obs


def _request(host, port, method, path, body=None, timeout=30):
    """(status, parsed JSON, headers) over stdlib http.client — an
    independent HTTP implementation exercising our server's framing."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(
            method, path,
            body=json.dumps(body) if isinstance(body, dict) else body,
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        raw = resp.read()
        doc = json.loads(raw) if raw else {}
        return resp.status, doc, dict(resp.getheaders())
    finally:
        conn.close()


@pytest.fixture
def two_bundle_server(tmp_path):
    """A running gateway over two tabular bundles (ephemeral port)."""
    b1 = _make_bundle(tmp_path, 0, "b1")
    b2 = _make_bundle(tmp_path, 1, "b2")
    # Permissive admission: these tests assert serving semantics, and a
    # loaded CI machine must not trip the default wait budget under them
    # (shedding has its own dedicated tests with forced budgets).
    gateway = build_gateway(
        [b1, b2], max_batch=4, max_wait_s=0.02,
        admission=AdmissionConfig(
            max_queue_depth=100_000, wait_budget_ms=100_000.0
        ),
    )
    with GatewayServer(gateway) as server:
        host, port = gateway.host, gateway.port
        yield gateway, host, port
    # server stopped (drained + bundles closed) by the context manager


class TestRegistry:
    def _engine_queue(self, tmp_path, seed, name):
        bundle = _make_bundle(tmp_path, seed, name)
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4)
        return engine, MicroBatchQueue(engine, max_wait_s=0.005)

    def test_register_route_swap(self, tmp_path):
        e1, q1 = self._engine_queue(tmp_path, 0, "b1")
        e2, q2 = self._engine_queue(tmp_path, 1, "b2")
        reg = BundleRegistry()
        h1 = reg.register(e1, q1)
        h2 = reg.register(e2, q2)
        assert h1 != h2 and reg.default_hash == h1
        # Duplicate config_hash is refused — routing would be ambiguous.
        with pytest.raises(ValueError, match="already registered"):
            reg.register(e1, q1)
        assert reg.route("house-1").config_hash == h1
        prev = reg.swap(h2)
        assert prev == h1 and reg.default_hash == h2
        assert reg.route("house-1").config_hash == h2
        q1.close()
        q2.close()

    def test_split_is_deterministic_and_pins(self, tmp_path):
        e1, q1 = self._engine_queue(tmp_path, 0, "b1")
        e2, q2 = self._engine_queue(tmp_path, 1, "b2")
        reg = BundleRegistry()
        h1 = reg.register(e1, q1)
        h2 = reg.register(e2, q2)
        reg.set_split(h2, 30.0)
        homes = [f"house-{i}" for i in range(64)]
        first = {h: reg.route(h).config_hash for h in homes}
        assert set(first.values()) == {h1, h2}  # both arms see traffic
        # Affinity: repeated routing never flips a household's bundle
        # (sessions carry cross-slot state).
        for h in homes:
            assert reg.route(h).config_hash == first[h]
        # ... even after the split percent changes (pins hold).
        reg.set_split(h2, 90.0)
        for h in homes:
            assert reg.route(h).config_hash == first[h]
        # Anonymous requests (no household id) always serve the DEFAULT,
        # whatever the split — hashing a constant empty id would dump ALL
        # anonymous traffic onto one arm instead of a percentage.
        assert reg.route(None).config_hash == h1
        assert reg.route("").config_hash == h1
        # A swap clears pins: everyone re-routes to the new default.
        reg.clear_split()
        reg.swap(h2)
        assert all(reg.route(h).config_hash == h2 for h in homes)
        q1.close()
        q2.close()

    def test_remove_guards_and_pin_cleanup(self, tmp_path):
        e1, q1 = self._engine_queue(tmp_path, 0, "b1")
        e2, q2 = self._engine_queue(tmp_path, 1, "b2")
        reg = BundleRegistry()
        h1 = reg.register(e1, q1)
        h2 = reg.register(e2, q2)
        with pytest.raises(ValueError, match="default"):
            reg.remove(h1)
        reg.set_split(h2, 50.0)
        with pytest.raises(ValueError, match="split"):
            reg.remove(h2)
        reg.clear_split()
        reg.swap(h2)
        removed = reg.remove(h1)
        assert removed.config_hash == h1
        assert reg.route("anyone").config_hash == h2
        with pytest.raises(KeyError):
            reg.swap(h1)
        q1.close()
        q2.close()

    def test_stats_snapshot(self, tmp_path):
        e1, q1 = self._engine_queue(tmp_path, 0, "b1")
        e2, q2 = self._engine_queue(tmp_path, 1, "b2")
        reg = BundleRegistry()
        h1 = reg.register(e1, q1)
        h2 = reg.register(e2, q2)
        # No split -> every route serves the default and records NO pin
        # (a pin per household id would grow without bound for zero
        # routing information at the millions-of-users scale).
        reg.route("house-1")
        s = reg.stats()
        assert s["default"] == h1
        assert s["bundles"][h1]["implementation"] == "tabular"
        assert s["bundles"][h1]["pinned_households"] == 0
        # Under a split, assignments pin (session affinity).
        reg.set_split(h2, 50.0)
        reg.route("house-1")
        assert reg.pinned_count == 1
        q1.close()
        q2.close()


class TestGatewayEndToEnd:
    """The ISSUE 5 acceptance path: concurrent network requests from
    multiple households across multiple padding buckets, bit-identical to
    direct engine calls."""

    def test_health_ready_stats(self, two_bundle_server):
        gateway, host, port = two_bundle_server
        status, doc, _ = _request(host, port, "GET", "/healthz")
        assert status == 200 and doc["ok"] is True
        status, doc, _ = _request(host, port, "GET", "/readyz")
        assert status == 200 and doc["ready"] is True
        # Readiness carries the ACTIVE default config_hash — the fleet
        # two-phase swap (serve/router.py) verifies the flip against it.
        assert doc["config_hash"] == gateway.registry.default_hash
        status, doc, _ = _request(host, port, "GET", "/stats")
        assert status == 200
        assert doc["kind"] == "gateway_stats"
        assert doc["default"] in doc["bundles"]
        assert len(doc["bundles"]) == 2

    def test_concurrent_households_two_buckets_bit_exact(
        self, two_bundle_server
    ):
        gateway, host, port = two_bundle_server
        default = gateway.registry.get(gateway.registry.default_hash)
        engine = default.engine
        obs = _obs(4, seed=7)

        # Phase 1: one lone household -> a bucket-1 batch.
        status, doc, _ = _request(
            host, port, "POST", "/v1/act",
            {"household": "house-solo", "obs": obs[0].tolist()},
        )
        assert status == 200
        # Phase 2: three households fired concurrently coalesce inside the
        # 20 ms window -> one batch of 3 padded to bucket 4.
        results = [None] * 3

        def fire(i):
            results[i] = _request(
                host, port, "POST", "/v1/act",
                {"household": f"house-{i}", "obs": obs[1 + i].tolist()},
            )

        threads = [
            threading.Thread(target=fire, args=(i,)) for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r[0] == 200 for r in results)

        # >= 2 padding buckets were exercised (1 and 4): 4 rows in >= 2
        # batches with at least one padded row.
        assert engine.stats["rows"] == 4
        assert engine.stats["batches"] >= 2
        assert engine.stats["padded_rows"] >= 1

        # Bit-exactness: network responses == direct engine.act on the
        # same observations (discrete policy guarantee holds across the
        # padding buckets the batches landed in).
        want = engine.act(obs)
        got = np.asarray(
            [doc["actions"]] + [r[1]["actions"] for r in results],
            dtype=np.float32,
        )
        np.testing.assert_array_equal(got, want)

    def test_batched_request(self, two_bundle_server):
        gateway, host, port = two_bundle_server
        engine = gateway.registry.get(gateway.registry.default_hash).engine
        obs = _obs(3, seed=11)
        status, doc, _ = _request(
            host, port, "POST", "/v1/act",
            {"household": "house-b", "obs": obs.tolist()},
        )
        assert status == 200
        np.testing.assert_array_equal(
            np.asarray(doc["actions"], np.float32), engine.act(obs)
        )

    def test_hot_swap_mid_traffic_zero_failures(self, two_bundle_server):
        gateway, host, port = two_bundle_server
        h1, h2 = gateway.registry.hashes
        assert gateway.registry.default_hash == h1
        obs = _obs(1)[0].tolist()
        statuses, hashes = [], []
        lock = threading.Lock()

        def fire(i):
            s, doc, _ = _request(
                host, port, "POST", "/v1/act",
                {"household": f"house-{i}", "obs": obs},
            )
            with lock:
                statuses.append(s)
                hashes.append(doc.get("config_hash"))

        # Wave 1 against bundle 1, swap to bundle 2 mid-stream, wave 2.
        wave1 = [
            threading.Thread(target=fire, args=(i,)) for i in range(8)
        ]
        for t in wave1:
            t.start()
        status, doc, _ = _request(
            host, port, "POST", "/admin/swap", {"config_hash": h2}
        )
        assert status == 200 and doc["default"] == h2
        wave2 = [
            threading.Thread(target=fire, args=(100 + i,)) for i in range(8)
        ]
        for t in wave2:
            t.start()
        for t in wave1 + wave2:
            t.join()
        # Zero failed requests across the swap, and both bundles served.
        assert statuses == [200] * 16
        assert h2 in hashes  # post-swap traffic reached the new default
        assert all(h in (h1, h2) for h in hashes)
        assert gateway.stats["swaps"] == 1

    def test_ab_split_routes_both_bundles(self, two_bundle_server):
        gateway, host, port = two_bundle_server
        h1, h2 = gateway.registry.hashes
        status, doc, _ = _request(
            host, port, "POST", "/admin/swap",
            {"split": {"config_hash": h2, "percent": 50.0}},
        )
        assert status == 200 and doc["split"]["config_hash"] == h2
        obs = _obs(1)[0].tolist()
        served = set()
        for i in range(32):
            s, d, _ = _request(
                host, port, "POST", "/v1/act",
                {"household": f"split-house-{i}", "obs": obs},
            )
            assert s == 200
            served.add(d["config_hash"])
        assert served == {h1, h2}
        # Stable assignment: the same household never flips arms.
        s, d, _ = _request(
            host, port, "POST", "/v1/act",
            {"household": "split-house-0", "obs": obs},
        )
        s2, d2, _ = _request(
            host, port, "POST", "/v1/act",
            {"household": "split-house-0", "obs": obs},
        )
        assert d["config_hash"] == d2["config_hash"]

    def test_admission_control_sheds_with_429(self, tmp_path):
        bundle = _make_bundle(tmp_path, 0, "b1")
        # Forced saturation: depth budget 1 and a wide coalescing window,
        # so concurrent requests pile behind the first and shed.
        gateway = build_gateway(
            [bundle], max_batch=4, max_wait_s=0.25,
            admission=AdmissionConfig(
                max_queue_depth=1, retry_after_s=2.5, min_wait_samples=10_000
            ),
        )
        with GatewayServer(gateway):
            host, port = gateway.host, gateway.port
            obs = _obs(1)[0].tolist()
            results = [None] * 6

            def fire(i):
                results[i] = _request(
                    host, port, "POST", "/v1/act",
                    {"household": f"h{i}", "obs": obs},
                )

            threads = [
                threading.Thread(target=fire, args=(i,)) for i in range(6)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            statuses = [r[0] for r in results]
            shed = [r for r in results if r[0] == 429]
            assert 200 in statuses  # the admitted head of the line served
            assert shed  # and the pile-up was shed, not queued forever
            # Shed responses carry Retry-After and an explanatory error.
            _, doc, headers = shed[0]
            assert headers.get("Retry-After") == "2.5"
            assert "queue depth" in doc["error"]
            assert gateway.stats["shed"] == len(shed)

    def test_wait_budget_sheds(self, tmp_path):
        bundle = _make_bundle(tmp_path, 0, "b1")
        gateway = build_gateway(
            [bundle], max_batch=4,
            admission=AdmissionConfig(
                wait_budget_ms=5.0, min_wait_samples=8
            ),
        )
        with GatewayServer(gateway):
            host, port = gateway.host, gateway.port
            # Stuff the queue's recent-wait window over budget — the
            # deterministic stand-in for a measured saturated tail.
            default = gateway.registry.get(gateway.registry.default_hash)
            now = time.monotonic()
            for _ in range(16):
                default.queue.recent_wait_ms.append((now, 100.0))
            status, doc, headers = _request(
                host, port, "POST", "/v1/act",
                {"household": "h", "obs": _obs(1)[0].tolist()},
            )
            assert status == 429
            assert "p95 queue wait" in doc["error"]
            assert "Retry-After" in headers
            # Recovery: shed requests never dispatch, so only AGE can
            # clear the window — samples older than wait_window_s must
            # stop shedding traffic (a burst must not shed forever).
            default.queue.recent_wait_ms.clear()
            stale = now - 2 * gateway.admission.wait_window_s
            for _ in range(16):
                default.queue.recent_wait_ms.append((stale, 100.0))
            status, doc, _ = _request(
                host, port, "POST", "/v1/act",
                {"household": "h", "obs": _obs(1)[0].tolist()},
            )
            assert status == 200


class TestQueueCancellation:
    def test_cancelled_future_does_not_starve_batchmates(self, tmp_path):
        """A caller abandoning its request (gateway timeout cancels through
        wrap_future) must not break result delivery to the other requests
        coalesced into the same batch."""
        bundle = _make_bundle(tmp_path, 0, "b1")
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4)
        engine.warmup(include_step=False)
        obs = _obs(3, seed=2)
        with MicroBatchQueue(engine, max_wait_s=0.2) as q:
            futs = [q.submit(obs[i]) for i in range(3)]
            assert futs[1].cancel()  # abandoned while still queued
            want = engine.act(obs)
            np.testing.assert_array_equal(futs[0].result(timeout=30), want[0])
            np.testing.assert_array_equal(futs[2].result(timeout=30), want[2])
            assert futs[1].cancelled()


class TestGatewayFailurePaths:
    def test_malformed_json_400(self, two_bundle_server):
        _, host, port = two_bundle_server
        status, doc, _ = _request(
            host, port, "POST", "/v1/act", body="{not json"
        )
        assert status == 400 and "JSON" in doc["error"]

    def test_wrong_shape_400(self, two_bundle_server):
        _, host, port = two_bundle_server
        status, doc, _ = _request(
            host, port, "POST", "/v1/act",
            {"household": "h", "obs": [[0.0] * 4] * (A + 2)},
        )
        assert status == 400 and "obs must be" in doc["error"]
        status, doc, _ = _request(
            host, port, "POST", "/v1/act", {"household": "h"}
        )
        assert status == 400 and "missing 'obs'" in doc["error"]

    def test_oversized_batch_413(self, tmp_path):
        bundle = _make_bundle(tmp_path, 0, "b1")
        gateway = build_gateway(
            [bundle], max_batch=4,
            admission=AdmissionConfig(max_request_rows=4),
        )
        with GatewayServer(gateway):
            host, port = gateway.host, gateway.port
            status, doc, _ = _request(
                host, port, "POST", "/v1/act",
                {"household": "h", "obs": _obs(5).tolist()},
            )
            assert status == 413 and "request limit" in doc["error"]

    def test_oversized_body_413(self, tmp_path):
        bundle = _make_bundle(tmp_path, 0, "b1")
        gateway = build_gateway(
            [bundle], max_batch=4,
            admission=AdmissionConfig(max_body_bytes=256),
        )
        with GatewayServer(gateway):
            host, port = gateway.host, gateway.port
            status, doc, _ = _request(
                host, port, "POST", "/v1/act",
                {"household": "h", "obs": _obs(4).tolist()},
            )
            assert status == 413 and "byte limit" in doc["error"]

    def test_unknown_config_hash_on_swap_404(self, two_bundle_server):
        _, host, port = two_bundle_server
        status, doc, _ = _request(
            host, port, "POST", "/admin/swap",
            {"config_hash": "deadbeef0000"},
        )
        assert status == 404 and "deadbeef0000" in doc["error"]
        # Split to an unknown arm is a 404 too.
        status, doc, _ = _request(
            host, port, "POST", "/admin/swap",
            {"split": {"config_hash": "deadbeef0000", "percent": 10}},
        )
        assert status == 404

    def test_swap_plus_bad_split_is_atomic(self, two_bundle_server):
        """A combined swap+split request that fails validation must apply
        NEITHER half — a 404 reply with the default already retargeted
        (and every pin cleared) would lie to the operator."""
        gateway, host, port = two_bundle_server
        h1, h2 = gateway.registry.hashes
        obs = _obs(1)[0].tolist()
        # Pin a household via a live split (pins only record under one).
        gateway.registry.set_split(h2, 50.0)
        _request(host, port, "POST", "/v1/act",
                 {"household": "pinned-house", "obs": obs})
        assert gateway.registry.pinned_count == 1
        status, doc, _ = _request(
            host, port, "POST", "/admin/swap",
            {"config_hash": h2,
             "split": {"config_hash": "deadbeef0000", "percent": 10}},
        )
        assert status == 404
        # Default unchanged, split unchanged, pins intact, no swap counted.
        assert gateway.registry.default_hash == h1
        assert gateway.registry.split == (h2, 50.0)
        assert gateway.registry.pinned_count == 1
        assert gateway.stats["swaps"] == 0
        gateway.registry.clear_split()
        # Bad percent on a valid arm: same atomicity.
        status, doc, _ = _request(
            host, port, "POST", "/admin/swap",
            {"config_hash": h2,
             "split": {"config_hash": h2, "percent": 250}},
        )
        assert status == 400
        assert gateway.registry.default_hash == h1

    def test_unknown_route_and_method(self, two_bundle_server):
        _, host, port = two_bundle_server
        assert _request(host, port, "GET", "/nope")[0] == 404
        assert _request(host, port, "GET", "/v1/act")[0] == 405
        assert _request(host, port, "POST", "/healthz", {})[0] == 405

    def test_engine_fault_answers_500_not_503(self, two_bundle_server):
        """Engine failures (XlaRuntimeError subclasses RuntimeError) must
        answer 500 — only the queue's shutdown race is a retriable 503."""
        gateway, host, port = two_bundle_server
        default = gateway.registry.get(gateway.registry.default_hash)
        original = default.engine.act
        try:
            def broken_act(obs):
                raise RuntimeError("simulated engine fault")

            default.engine.act = broken_act
            status, doc, _ = _request(
                host, port, "POST", "/v1/act",
                {"household": "h", "obs": _obs(1)[0].tolist()},
            )
            assert status == 500
            assert "simulated engine fault" in doc["error"]
        finally:
            default.engine.act = original

    def test_header_flood_bounded_400(self, two_bundle_server):
        """An endless header stream must be cut off, not accumulated."""
        _, host, port = two_bundle_server
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            conn.putrequest("GET", "/healthz")
            for i in range(200):
                conn.putheader(f"x-flood-{i}", "y")
            conn.endheaders()
            resp = conn.getresponse()
            doc = json.loads(resp.read())
            assert resp.status == 400
            assert "too many headers" in doc["error"]
        finally:
            conn.close()

    def test_request_mid_drain_503(self, two_bundle_server):
        gateway, host, port = two_bundle_server
        status, doc, _ = _request(host, port, "POST", "/admin/drain", {})
        assert status == 200 and doc["draining"] is True
        # Readiness flips; act requests are refused with Retry-After.
        status, doc, _ = _request(host, port, "GET", "/readyz")
        assert status == 503 and doc["reason"] == "draining"
        status, doc, headers = _request(
            host, port, "POST", "/v1/act",
            {"household": "h", "obs": _obs(1)[0].tolist()},
        )
        assert status == 503 and "draining" in doc["error"]
        assert "Retry-After" in headers
        # Liveness is unaffected (the pod is healthy, just not ready).
        assert _request(host, port, "GET", "/healthz")[0] == 200


class TestNetworkServeBench:
    def test_rows_and_warehouse_traces_keyed_by_bundle_hash(self, tmp_path):
        """Acceptance: serve-bench --network measures wire percentiles and
        its per-request traces land in the warehouse joined on the SERVING
        bundle's config_hash."""
        from p2pmicrogrid_tpu.data.results import ResultsStore

        bundle = _make_bundle(tmp_path, 0, "b1")
        db = str(tmp_path / "r.db")
        # Admission effectively off: this test asserts every request is
        # served and traced — on a loaded CI machine the default 50 ms
        # wait budget can legitimately shed (covered by its own tests).
        gateway = build_gateway(
            [bundle], max_batch=4, max_wait_s=0.002, results_db=db,
            admission=AdmissionConfig(
                max_queue_depth=100_000, wait_budget_ms=100_000.0
            ),
        )
        with GatewayServer(gateway):
            host, port = gateway.host, gateway.port
            bundle_hash = gateway.registry.default_hash
            rows = serve_bench_network(
                host, port, n_agents=A, rate_hz=400.0, n_requests=48,
                n_households=4, seed=5,
            )
        head = rows[-1]
        assert head["metric"] == "serve_bench_network"
        for key in ("p50_ms", "p95_ms", "p99_ms", "throughput_rps",
                    "shed_rate"):
            assert isinstance(head[key], (int, float))
        assert head["n_ok"] == 48 and head["shed_rate"] == 0.0
        assert head["served_config_hashes"] == [bundle_hash]
        metrics = [r["metric"] for r in rows]
        assert metrics[:3] == [
            "serve_gateway_latency_ms_p50",
            "serve_gateway_latency_ms_p95",
            "serve_gateway_latency_ms_p99",
        ]
        # Warehouse: one serve_request trace per wire request, on a run
        # whose manifest identity IS the serving bundle's config_hash.
        with ResultsStore(db) as store:
            (n_traces,) = store.con.execute(
                "SELECT COUNT(*) FROM telemetry_points p "
                "JOIN telemetry_runs r ON r.run_id = p.run_id "
                "WHERE p.kind = 'serve_request' AND r.config_hash = ?",
                (bundle_hash,),
            ).fetchone()
            assert n_traces == 48

    def test_serve_bench_network_cli_one_json_per_line(self, capfd):
        from p2pmicrogrid_tpu.cli import main

        rc = main([
            "serve-bench", "--network", "--agents", "2",
            "--implementation", "tabular", "--requests", "24",
            "--rate", "400", "--max-batch", "4", "--max-wait-ms", "1",
            "--households", "3",
        ])
        assert rc == 0
        out, err = capfd.readouterr()
        lines = [l for l in out.splitlines() if l.strip()]
        rows = [json.loads(l) for l in lines]  # every stdout line is JSON
        assert rows[-1]["metric"] == "serve_bench_network"
        assert "gateway on" in err


class TestGatewayCli:
    def test_serve_gateway_bounded_run_writes_stats(self, tmp_path, capfd):
        import importlib.util
        import os

        from p2pmicrogrid_tpu.cli import main

        stats_path = str(tmp_path / "GATEWAY_STATS_test.json")
        rc = main([
            "serve-gateway", "--agents", "2", "--implementation", "tabular",
            "--port", "0", "--max-batch", "4", "--serve-seconds", "0.3",
            "--stats-out", stats_path,
        ])
        assert rc == 0
        out, err = capfd.readouterr()
        listening = json.loads(
            [l for l in out.splitlines() if l.strip()][0]
        )
        assert listening["kind"] == "gateway_listening"
        assert listening["port"] > 0
        assert listening["default"] in listening["bundles"]
        assert "fresh-init" in err
        # The final snapshot validates against the committed-capture schema.
        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "check_artifacts_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        problems: list = []
        mod.check_gateway_stats(stats_path, problems)
        assert problems == []

    def test_stats_snapshot_schema_round_trip(self, two_bundle_server, tmp_path):
        import importlib.util
        import os

        gateway, host, port = two_bundle_server
        _request(
            host, port, "POST", "/v1/act",
            {"household": "h", "obs": _obs(1)[0].tolist()},
        )
        status, doc, _ = _request(host, port, "GET", "/stats")
        assert status == 200
        path = tmp_path / "GATEWAY_STATS_r0.json"
        path.write_text(json.dumps(doc))
        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "check_artifacts_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        problems: list = []
        mod.check_gateway_stats(str(path), problems)
        assert problems == []
        # A broken snapshot is caught.
        bad = dict(doc, default="not-a-bundle")
        path.write_text(json.dumps(bad))
        problems = []
        mod.check_gateway_stats(str(path), problems)
        assert any("default" in p for p in problems)

    def test_build_gateway_partial_failure_leaks_nothing(self, tmp_path):
        """A later bundle failing to load must close the earlier bundles'
        queue workers and telemetry (the caller only gets an exception)."""
        bundle = _make_bundle(tmp_path, 0, "b1")
        before = threading.active_count()
        with pytest.raises(FileNotFoundError):
            build_gateway(
                [bundle, str(tmp_path / "does-not-exist")], max_batch=4
            )
        # The first bundle's MicroBatchQueue worker thread was joined.
        assert threading.active_count() == before

    def test_start_failure_surfaces_and_stop_is_fast(self, tmp_path):
        """A bind failure must raise the real error from start(), and the
        follow-up stop() must return immediately instead of timing out on
        a loop that never ran."""
        import socket

        bundle = _make_bundle(tmp_path, 0, "b1")
        blocker = socket.socket()
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        taken_port = blocker.getsockname()[1]
        try:
            gateway = build_gateway(
                [bundle], max_batch=4, port=taken_port, warmup=False
            )
            server = GatewayServer(gateway)
            with pytest.raises(OSError):
                server.start()
            t0 = time.monotonic()
            server.stop()  # must short-circuit, not block ~35 s
            assert time.monotonic() - t0 < 1.0
            # The owned bundles were cleaned up on the failure path: no
            # leaked queue worker threads, no unflushed telemetry.
            for h in gateway.registry.hashes:
                assert gateway.registry.get(h).queue._closed
        finally:
            blocker.close()

    def test_gateway_jsonl_schema(self, tmp_path):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "check_artifacts_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        good = {
            "metric": "serve_bench_network", "value": 1.0, "unit": "ms",
            "vs_baseline": 1.0, "p50_ms": 0.5, "p95_ms": 0.9,
            "p99_ms": 1.0, "throughput_rps": 100.0, "shed_rate": 0.0,
        }
        path = tmp_path / "SERVE_GATEWAY_r01.jsonl"
        path.write_text(json.dumps(good) + "\n")
        problems: list = []
        mod.check_gateway_jsonl(str(path), problems)
        assert problems == []
        bad = {k: v for k, v in good.items() if k != "shed_rate"}
        path.write_text(json.dumps(bad) + "\n")
        problems = []
        mod.check_gateway_jsonl(str(path), problems)
        assert any("shed_rate" in p for p in problems)
