"""Tests for no-com communities, PV-drop fault injection, and the
semi-intelligent baseline."""

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.data import synthetic_traces
from p2pmicrogrid_tpu.envs import (
    build_episode_arrays,
    init_physical,
    make_ratings,
    rule_baseline_episode,
    run_episode,
    semi_intelligent_baseline_episode,
    with_pv_drop,
)
from p2pmicrogrid_tpu.train import init_policy_state, make_policy


@pytest.fixture(scope="module")
def day_traces():
    return synthetic_traces(n_days=1, start_day=11).normalized()


class TestNoCom:
    def test_setting_string(self):
        cfg = default_config(sim=SimConfig(n_agents=2, trading=False, homogeneous=True))
        assert cfg.setting == "2-multi-agent-no-com-homo"
        cfg = default_config(sim=SimConfig(n_agents=3, rounds=2))
        assert cfg.setting == "3-multi-agent-com-rounds-2-hetero"

    def test_no_p2p_power_and_learning_works(self, day_traces):
        cfg = default_config(
            sim=SimConfig(n_agents=2, trading=False),
            train=TrainConfig(implementation="tabular"),
        )
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        phys = init_physical(cfg, jax.random.PRNGKey(0))
        _, ps2, out = run_episode(
            cfg, policy, ps, phys, arrays, ratings, jax.random.PRNGKey(7), training=True
        )
        np.testing.assert_allclose(np.asarray(out.p_p2p), 0.0)
        assert float(np.abs(np.asarray(ps2.q_table - ps.q_table)).max()) > 0
        # Grid power carries the whole balance + heat pump.
        assert out.decisions.shape == (96, 1, 2)

    def test_com_vs_no_com_differ(self, day_traces):
        outs = {}
        for trading in (True, False):
            cfg = default_config(
                sim=SimConfig(n_agents=2, trading=trading),
                train=TrainConfig(implementation="tabular"),
            )
            ratings = make_ratings(cfg, np.random.default_rng(42))
            arrays = build_episode_arrays(cfg, day_traces, ratings)
            policy = make_policy(cfg)
            ps = init_policy_state(cfg, jax.random.PRNGKey(1))
            ps = ps._replace(
                q_table=jax.random.normal(jax.random.PRNGKey(5), ps.q_table.shape)
            )
            phys = init_physical(cfg, jax.random.PRNGKey(0))
            _, _, out = run_episode(
                cfg, policy, ps, phys, arrays, ratings, jax.random.PRNGKey(7),
                training=False,
            )
            outs[trading] = np.asarray(out.cost).sum()
        assert outs[True] != outs[False]


class TestPvDrop:
    def test_drop_zeroes_pv_from_slot(self, day_traces):
        cfg = default_config(sim=SimConfig(n_agents=2))
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        dropped = with_pv_drop(arrays, agent=1, start_slot=48, factor=0.0)
        np.testing.assert_array_equal(
            np.asarray(dropped.pv_w[:48, 1]), np.asarray(arrays.pv_w[:48, 1])
        )
        np.testing.assert_allclose(np.asarray(dropped.pv_w[48:, 1]), 0.0)
        # Other agent untouched.
        np.testing.assert_array_equal(
            np.asarray(dropped.pv_w[:, 0]), np.asarray(arrays.pv_w[:, 0])
        )

    def test_partial_factor(self, day_traces):
        cfg = default_config(sim=SimConfig(n_agents=2))
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        dropped = with_pv_drop(arrays, agent=0, start_slot=0, factor=0.5)
        np.testing.assert_allclose(
            np.asarray(dropped.pv_w[:, 0]),
            np.asarray(arrays.pv_w[:, 0]) * 0.5,
            rtol=1e-6,
        )


class TestSemiIntelligent:
    def test_holds_comfort_and_preheats(self, day_traces):
        cfg = default_config(sim=SimConfig(n_agents=2))
        ratings = make_ratings(cfg, np.random.default_rng(42))
        arrays = build_episode_arrays(cfg, day_traces, ratings)
        phys = init_physical(cfg, jax.random.PRNGKey(0))
        _, semi = semi_intelligent_baseline_episode(cfg, phys, arrays)
        _, rule = rule_baseline_episode(cfg, phys, arrays)
        assert float(semi.t_in.min()) > 18.5
        # Pre-heating buys more energy overall...
        assert float(semi.hp_power_w.sum()) > float(rule.hp_power_w.sum())
        # ...but concentrated in cheap slots: its mean purchase price is lower.
        semi_price = (semi.hp_power_w * semi.buy_price[:, None]).sum() / semi.hp_power_w.sum()
        rule_price = (rule.hp_power_w * rule.buy_price[:, None]).sum() / (rule.hp_power_w.sum() + 1e-9)
        assert float(semi_price) < float(rule_price) + 1e-3
