"""Serving layer: bundle export/load round-trips are bit-exact against the
training-side greedy paths, padding buckets never change outputs, sessions
carry state, the microbatch queue coalesces correctly, loadgen percentiles
are seed-deterministic, and the serve-bench CLI keeps stdout strictly
one-JSON-per-line. Fast and JAX_PLATFORMS=cpu-safe by design (tier-1)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    DDPGConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.models.dqn import ACTION_VALUES
from p2pmicrogrid_tpu.serve import (
    MicroBatchQueue,
    PolicyEngine,
    export_bundle_from_checkpoint,
    export_policy_bundle,
    load_policy_bundle,
    plan_open_loop,
    poisson_arrivals,
    serve_bench,
)
from p2pmicrogrid_tpu.train import init_policy_state

A = 3  # community size for all serving tests


def _cfg(impl, **ddpg_kw):
    return default_config(
        sim=SimConfig(n_agents=A),
        train=TrainConfig(implementation=impl),
        ddpg=DDPGConfig(buffer_size=16, batch_size=2, **ddpg_kw),
    )


def _obs(n, seed=0):
    rng = np.random.default_rng(seed)
    obs = np.empty((n, A, 4), dtype=np.float32)
    obs[..., 0] = rng.uniform(0, 1, (n, A))
    obs[..., 1:] = rng.uniform(-1, 1, (n, A, 3))
    return obs


def _trained_state(cfg, seed=0):
    """A state with non-trivial greedy structure (random, not trained —
    bit-exactness does not care, but an all-zero Q-table would make every
    argmax trivially 0)."""
    ps = init_policy_state(cfg, jax.random.PRNGKey(seed))
    if cfg.train.implementation == "tabular":
        ps = ps._replace(
            q_table=jax.random.normal(
                jax.random.PRNGKey(seed + 1), ps.q_table.shape
            )
        )
    return ps


def _reference_actions(cfg, ps, obs):
    """Greedy actions through the TRAINING-side code paths."""
    impl = cfg.train.implementation
    key = jax.random.PRNGKey(0)
    if impl == "tabular":
        from p2pmicrogrid_tpu.models.tabular import tabular_act

        def one(o):
            action, _ = tabular_act(cfg.qlearning, ps, o, key, explore=False)
            return ACTION_VALUES[action]

        return np.asarray(jax.vmap(one)(jnp.asarray(obs)))
    if impl == "dqn":
        from p2pmicrogrid_tpu.models.dqn import dqn_act

        def one(o):
            action, _ = dqn_act(cfg.dqn, ps, o, key, explore=False)
            return ACTION_VALUES[action]

        return np.asarray(jax.vmap(one)(jnp.asarray(obs)))
    # ddpg: the scenario-batched greedy act (what health evals serve with).
    from p2pmicrogrid_tpu.models.ddpg import DDPGParams, ddpg_shared_act

    params = DDPGParams(
        actor=ps.actor,
        critic=ps.critic,
        actor_target=ps.actor_target,
        critic_target=ps.critic_target,
        actor_opt=ps.actor_opt,
        critic_opt=ps.critic_opt,
        noise_scale=ps.noise_scale,
    ) if not isinstance(ps, DDPGParams) else ps
    a, _, _ = ddpg_shared_act(
        cfg.ddpg, params, jnp.asarray(obs),
        jnp.zeros(obs.shape[:2]), key, explore=False,
    )
    return np.asarray(a)


class TestBundleRoundTrip:
    @pytest.mark.parametrize("impl", ["tabular", "dqn"])
    def test_export_load_act_bit_exact(self, impl, tmp_path):
        cfg = _cfg(impl)
        ps = _trained_state(cfg)
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "b"))
        manifest, params = load_policy_bundle(bundle)
        assert manifest["kind"] == "policy_bundle"
        assert manifest["implementation"] == impl
        assert manifest["n_agents"] == A
        assert manifest["config_hash"]
        assert manifest["obs_spec"]["dim"] == 4

        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        obs = _obs(4)
        got = engine.act(obs)
        want = _reference_actions(cfg, ps, obs)
        np.testing.assert_array_equal(got, want)

    def test_export_load_act_ddpg_ulp_exact(self, tmp_path):
        # Continuous actor: the engine's fused program matches the
        # training-side greedy act to ~1 ulp, not bit-for-bit (engine.py
        # "Bit-exact greedy" caveat); the discrete policies above carry the
        # bit-identical guarantee.
        cfg = _cfg("ddpg")
        ps = _trained_state(cfg)
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "b"))
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        obs = _obs(4)
        np.testing.assert_allclose(
            engine.act(obs), _reference_actions(cfg, ps, obs), rtol=1e-6
        )

    def test_agent_shared_ddpg_bundle(self, tmp_path):
        from p2pmicrogrid_tpu.models.ddpg import ddpg_params_init

        cfg = _cfg("ddpg", share_across_agents=True)
        ps = ddpg_params_init(cfg.ddpg, A, jax.random.PRNGKey(0))
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "b"))
        manifest, _ = load_policy_bundle(bundle)
        assert manifest["model"]["share_across_agents"] is True

        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        for n in (4, 5):  # exact bucket and a padded one
            obs = _obs(n)
            np.testing.assert_allclose(
                engine.act(obs), _reference_actions(cfg, ps, obs), rtol=1e-6
            )

    def test_bundle_excludes_learner_state(self, tmp_path):
        # The bundle is the greedy subtree ONLY: no optimizer moments, no
        # replay rings, no target copies.
        cfg = _cfg("ddpg")
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "b"))
        with np.load(str(tmp_path / "b" / "params.npz")) as z:
            keys = set(z.files)
        assert all(
            not k.startswith(("critic", "actor_target", "critic_target",
                              "actor_opt", "critic_opt", "replay", "ou_"))
            for k in keys
        )
        manifest, _ = load_policy_bundle(bundle)
        # actor MLP: 3 Dense layers x (kernel, bias) per agent
        assert manifest["param_count"] == sum(
            np.prod(s) for s in [
                (A, 4, 64), (A, 64), (A, 64, 64), (A, 64), (A, 64, 1), (A, 1),
            ]
        )

    def test_newer_format_version_refused(self, tmp_path):
        cfg = _cfg("tabular")
        bundle = export_policy_bundle(
            cfg, _trained_state(cfg), str(tmp_path / "b")
        )
        mpath = tmp_path / "b" / "manifest.json"
        m = json.loads(mpath.read_text())
        m["format_version"] = 99
        mpath.write_text(json.dumps(m))
        with pytest.raises(ValueError, match="format_version"):
            load_policy_bundle(bundle)

    def test_float16_bundle_halves_disk_and_still_serves(self, tmp_path):
        cfg = _cfg("ddpg")
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        b32 = export_policy_bundle(cfg, ps, str(tmp_path / "f32"))
        b16 = export_policy_bundle(
            cfg, ps, str(tmp_path / "f16"), dtype="float16"
        )
        m32, _ = load_policy_bundle(b32)
        m16, _ = load_policy_bundle(b16)
        assert m16["param_bytes"] == m32["param_bytes"] // 2
        engine = PolicyEngine(bundle_dir=b16)
        out = engine.act(_obs(2))
        # Quantized, not bit-exact — but the same policy to f16 tolerance.
        np.testing.assert_allclose(
            out, _reference_actions(cfg, ps, _obs(2)), atol=2e-3
        )


class TestCheckpointToBundle:
    @pytest.mark.parametrize("impl", ["tabular", "dqn"])
    def test_checkpoint_export_bit_exact_across_two_buckets(self, impl, tmp_path):
        """Acceptance: bundle greedy actions are bit-identical to the source
        checkpoint's, across at least two padding buckets. Discrete policies
        carry the guarantee (argmax absorbs per-shape gemm retiling); the
        continuous actor's cross-bucket ulp caveat is covered in
        TestBundleRoundTrip."""
        from p2pmicrogrid_tpu.train.checkpoint import save_checkpoint

        cfg = _cfg(impl)
        ps = _trained_state(cfg)
        ckpt_dir = str(tmp_path / "ckpt")
        save_checkpoint(ckpt_dir, ps, episode=7)
        bundle = export_bundle_from_checkpoint(
            cfg, ckpt_dir, str(tmp_path / "bundle")
        )
        manifest, _ = load_policy_bundle(bundle)
        assert manifest["source"]["episode"] == 7

        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        obs = _obs(5, seed=3)
        want = _reference_actions(cfg, ps, obs)
        # Batch 3 pads to bucket 4; batch 5 pads to bucket 8 — two distinct
        # compiled programs must both reproduce the checkpoint bit-exactly.
        got3 = engine.act(obs[:3])
        got5 = engine.act(obs)
        assert engine.bucket_for(3) == 4 and engine.bucket_for(5) == 8
        np.testing.assert_array_equal(got3, want[:3])
        np.testing.assert_array_equal(got5, want)
        assert engine.stats["padded_rows"] == (4 - 3) + (8 - 5)

    def test_ddpg_checkpoint_export_ulp_exact(self, tmp_path):
        """The raw-restore export path works for the actor-critic state too
        (continuous actor: ulp tolerance, see engine.py)."""
        from p2pmicrogrid_tpu.train.checkpoint import save_checkpoint

        cfg = _cfg("ddpg")
        ps = _trained_state(cfg)
        ckpt_dir = str(tmp_path / "ckpt")
        save_checkpoint(ckpt_dir, ps, episode=1)
        bundle = export_bundle_from_checkpoint(
            cfg, ckpt_dir, str(tmp_path / "bundle")
        )
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        obs = _obs(4, seed=4)
        np.testing.assert_allclose(
            engine.act(obs), _reference_actions(cfg, ps, obs), rtol=1e-6
        )


class TestEngine:
    def test_padding_never_changes_outputs(self, tmp_path):
        cfg = _cfg("tabular")
        ps = _trained_state(cfg)
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "b"))
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4)
        obs = _obs(11, seed=5)
        # 11 rows through max_batch 4 = batches of 4+4+3 (last padded to 4).
        got = engine.act(obs)
        np.testing.assert_array_equal(got, _reference_actions(cfg, ps, obs))
        assert engine.stats["batches"] == 3
        assert engine.stats["padded_rows"] == 1
        assert 0.0 < engine.padding_waste < 0.1

    def test_warmup_compiles_buckets(self, tmp_path):
        cfg = _cfg("tabular")
        bundle = export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        assert engine.buckets == [1, 2, 4, 8]
        assert engine.warmup() == [1, 2, 4, 8]

    def test_rejects_wrong_community_shape(self, tmp_path):
        cfg = _cfg("tabular")
        bundle = export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        engine = PolicyEngine(bundle_dir=bundle)
        with pytest.raises(ValueError, match=r"\[B, 3, 4\]"):
            engine.act(np.zeros((2, A + 1, 4), np.float32))

    def test_sessions_carry_state_with_donated_step(self, tmp_path):
        cfg = _cfg("ddpg")
        ps = init_policy_state(cfg, jax.random.PRNGKey(0))
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "b"))
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        sessions = engine.init_sessions(3)
        obs1, obs2 = _obs(3, seed=1), _obs(3, seed=2)
        sessions, a1 = engine.step(sessions, obs1)
        np.testing.assert_array_equal(a1, _reference_actions(cfg, ps, obs1))
        np.testing.assert_array_equal(np.asarray(sessions.hp_frac), a1)
        sessions, a2 = engine.step(sessions, obs2)
        np.testing.assert_array_equal(np.asarray(sessions.hp_frac), a2)
        assert np.asarray(sessions.slots).tolist() == [2, 2, 2]

    def test_microbatch_queue_matches_direct_act(self, tmp_path):
        cfg = _cfg("tabular")
        ps = _trained_state(cfg)
        bundle = export_policy_bundle(cfg, ps, str(tmp_path / "b"))
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8)
        engine.warmup()
        obs = _obs(6, seed=9)
        want = _reference_actions(cfg, ps, obs)
        with MicroBatchQueue(engine, max_wait_s=0.01) as q:
            futs = [q.submit(obs[i]) for i in range(6)]
            for i, fut in enumerate(futs):
                np.testing.assert_array_equal(fut.result(timeout=30), want[i])

    def test_serve_counters_reach_telemetry(self, tmp_path):
        from p2pmicrogrid_tpu.telemetry import Telemetry

        cfg = _cfg("tabular")
        bundle = export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        tel = Telemetry(run_id="t")
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4, telemetry=tel)
        engine.act(_obs(3))
        s = tel.summary()
        assert s["counters"]["serve.requests"] == 3
        assert s["counters"]["serve.batches"] == 1
        assert s["counters"]["serve.padded_rows"] == 1
        assert s["histograms"]["serve.batch_ms"]["count"] == 1


class TestLoadgen:
    def test_poisson_arrivals_deterministic(self):
        a = poisson_arrivals(100.0, 50, seed=7)
        b = poisson_arrivals(100.0, 50, seed=7)
        np.testing.assert_array_equal(a, b)
        assert (np.diff(a) > 0).all()

    def test_plan_percentiles_deterministic_under_seed(self):
        def run():
            arrivals = poisson_arrivals(1000.0, 200, seed=11)
            res = plan_open_loop(
                arrivals,
                service_time_fn=lambda i, j: 0.0005 * (j - i) + 0.001,
                max_batch=8,
                max_wait_s=0.002,
                bucket_fn=lambda n: 1 << (n - 1).bit_length() if n > 1 else 1,
            )
            return res.latency_ms(50), res.latency_ms(95), res.latency_ms(99)

        assert run() == run()

    def test_plan_semantics(self):
        # 4 simultaneous arrivals, max_batch 2, zero wait: two batches of 2,
        # serial service, second batch waits for the first.
        arrivals = np.array([0.0, 0.0, 0.0, 0.0])
        res = plan_open_loop(
            arrivals, lambda i, j: 1.0, max_batch=2, max_wait_s=0.0
        )
        assert res.batch_sizes == [2, 2]
        np.testing.assert_allclose(res.latencies_s, [1.0, 1.0, 2.0, 2.0])
        assert res.throughput_rps == pytest.approx(2.0)

    def test_padding_waste_accounting(self):
        arrivals = np.array([0.0, 0.0, 0.0])
        res = plan_open_loop(
            arrivals, lambda i, j: 1.0, max_batch=4, max_wait_s=0.0,
            bucket_fn=lambda n: 4,
        )
        assert res.batch_sizes == [3]
        assert res.padding_waste == pytest.approx(0.25)

    def test_serve_bench_rows(self, tmp_path):
        cfg = _cfg("tabular")
        bundle = export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4)
        emitted = []
        rows = serve_bench(
            engine, rate_hz=5000.0, n_requests=64, max_batch=4,
            max_wait_s=0.001, seed=3, emit=emitted.append,
        )
        assert rows == emitted
        metrics = [r["metric"] for r in rows]
        assert metrics[:3] == [
            "serve_latency_ms_p50", "serve_latency_ms_p95",
            "serve_latency_ms_p99",
        ]
        assert "serve_throughput_rps" in metrics
        assert "serve_padding_waste" in metrics
        head = rows[-1]
        assert head["metric"] == "serve_bench"
        for key in ("p50_ms", "p95_ms", "p99_ms", "throughput_rps",
                    "padding_waste", "config_hash"):
            assert key in head
        # Every row satisfies the metric-row schema the checker enforces.
        for r in rows:
            assert isinstance(r["metric"], str)
            assert isinstance(r["value"], (int, float))
            assert isinstance(r["unit"], str)
            assert isinstance(r["vs_baseline"], (int, float))


class TestServeTracing:
    """Per-request trace records (ISSUE 3): enqueue->dispatch wait, bucket,
    padding and batch service span flow through the telemetry sinks — into
    the SQLite warehouse when one is attached."""

    def test_queue_emits_per_request_traces(self, tmp_path):
        from p2pmicrogrid_tpu.telemetry import MemorySink, Telemetry

        cfg = _cfg("tabular")
        bundle = export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        sink = MemorySink()
        tel = Telemetry(run_id="t", sinks=[sink])
        engine = PolicyEngine(bundle_dir=bundle, max_batch=8, telemetry=tel)
        engine.warmup(include_step=False)
        obs = _obs(5)
        with MicroBatchQueue(engine, max_wait_s=0.01) as q:
            futs = [q.submit(obs[i]) for i in range(5)]
            for fut in futs:
                fut.result(timeout=30)
        traces = [r for r in sink.records if r.get("kind") == "serve_request"]
        assert len(traces) == 5
        for t in traces:
            assert t["source"] == "queue"
            assert t["wait_ms"] >= 0
            assert t["service_ms"] > 0
            assert t["latency_ms"] >= t["wait_ms"]
            assert t["bucket"] >= t["batch_size"]
            assert t["padded_rows"] == t["bucket"] - t["batch_size"]
        # The coalescing wait also aggregates as a histogram.
        assert tel.summary()["histograms"]["serve.queue_wait_ms"]["count"] == 5

    def test_plan_open_loop_records_batch_schedule(self):
        arrivals = np.array([0.0, 0.0, 0.0, 0.0])
        res = plan_open_loop(
            arrivals, lambda i, j: 1.0, max_batch=2, max_wait_s=0.0
        )
        assert res.batch_starts == [0, 2]
        assert res.service_s == [1.0, 1.0]
        # Batch 2 dispatches when the server frees (t=1), not at arrival.
        assert res.dispatch_s == [0.0, 1.0]

    def test_serve_bench_traces_reach_sqlite_warehouse(self, tmp_path):
        """Acceptance: serve-bench emits per-request trace records into the
        same store training telemetry lands in."""
        from p2pmicrogrid_tpu.data.results import ResultsStore
        from p2pmicrogrid_tpu.telemetry import (
            SqliteSink,
            Telemetry,
            set_current,
        )

        cfg = _cfg("tabular")
        bundle = export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        db = str(tmp_path / "r.db")
        tel = Telemetry(
            run_id="serve-test", sinks=[SqliteSink(db)],
            manifest={"config_hash": "serve-cfg", "created": "t"},
        )
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4, telemetry=tel)
        set_current(tel)
        try:
            serve_bench(
                engine, rate_hz=5000.0, n_requests=32, max_batch=4,
                max_wait_s=0.001, seed=3, emit=tel.emit,
            )
        finally:
            set_current(None)
            tel.close()
        with ResultsStore(db) as store:
            traces = store.con.execute(
                "SELECT COUNT(*) FROM telemetry_points "
                "WHERE kind='serve_request'"
            ).fetchone()[0]
            assert traces == 32
            # The headline metric row is queryable next to the traces.
            (p99,) = store.con.execute(
                "SELECT value FROM telemetry_points "
                "WHERE kind='metric' AND name='serve_bench'"
            ).fetchone()
            assert p99 > 0
            # Per-bucket compile profiles (warmup hooks) landed as gauges.
            buckets = store.con.execute(
                "SELECT COUNT(*) FROM telemetry_points WHERE kind='gauge' "
                "AND name LIKE 'profile.serve_bucket_%.flops'"
            ).fetchone()[0]
            assert buckets >= 1

    def test_sinkless_serve_bench_skips_traces(self, tmp_path):
        """Without sinks (plain serve_bench call), no per-request events are
        built — rows still come back."""
        from p2pmicrogrid_tpu.telemetry import Telemetry, set_current

        cfg = _cfg("tabular")
        bundle = export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4)
        tel = Telemetry(run_id="t")
        set_current(tel)
        try:
            rows = serve_bench(
                engine, rate_hz=5000.0, n_requests=16, max_batch=4,
                max_wait_s=0.001, emit=None,
            )
        finally:
            set_current(None)
        assert rows[-1]["metric"] == "serve_bench"

    def test_warmup_profiles_each_bucket(self, tmp_path):
        """Acceptance: HLO flops + peak-memory gauges appear for at least
        one serve padding bucket."""
        from p2pmicrogrid_tpu.telemetry import Telemetry

        cfg = _cfg("tabular")
        bundle = export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        tel = Telemetry(run_id="t")
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4, telemetry=tel)
        engine.warmup(include_step=False)
        g = tel.summary()["gauges"]
        for b in (1, 2, 4):
            assert g[f"profile.serve_bucket_{b}.flops"] > 0
            assert g[f"profile.serve_bucket_{b}.peak_bytes"] > 0

    def test_warmup_profile_kill_switch(self, tmp_path, monkeypatch):
        from p2pmicrogrid_tpu.telemetry import Telemetry

        monkeypatch.setenv("P2P_PROFILE", "0")
        cfg = _cfg("tabular")
        bundle = export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        tel = Telemetry(run_id="t")
        engine = PolicyEngine(bundle_dir=bundle, max_batch=4, telemetry=tel)
        engine.warmup(include_step=False)
        assert not any(
            k.startswith("profile.") for k in tel.summary()["gauges"]
        )


class TestServeCli:
    def test_serve_bench_cli_one_json_per_line(self, capfd):
        from p2pmicrogrid_tpu.cli import main

        rc = main([
            "serve-bench", "--agents", "2", "--implementation", "tabular",
            "--requests", "48", "--rate", "5000", "--max-batch", "8",
            "--max-wait-ms", "1",
        ])
        assert rc == 0
        out, err = capfd.readouterr()
        lines = [l for l in out.splitlines() if l.strip()]
        assert len(lines) == 6  # 3 latency + throughput + waste + headline
        rows = [json.loads(l) for l in lines]
        assert rows[-1]["metric"] == "serve_bench"
        assert "fresh-init" in err

    def test_export_bundle_cli(self, tmp_path, capsys):
        from p2pmicrogrid_tpu.cli import main
        from p2pmicrogrid_tpu.train.checkpoint import (
            checkpoint_dir,
            save_checkpoint,
        )

        cfg = _cfg("tabular")
        ps = _trained_state(cfg)
        model_dir = str(tmp_path / "models")
        save_checkpoint(
            checkpoint_dir(model_dir, cfg.setting, "tabular"), ps, episode=3
        )
        out_dir = str(tmp_path / "bundle")
        rc = main([
            "export-bundle", "--agents", str(A), "--implementation",
            "tabular", "--model-dir", model_dir, "--out", out_dir,
        ])
        assert rc == 0
        manifest, _ = load_policy_bundle(out_dir)
        assert manifest["implementation"] == "tabular"
        engine = PolicyEngine(bundle_dir=out_dir)
        obs = _obs(2)
        np.testing.assert_array_equal(
            engine.act(obs), _reference_actions(cfg, ps, obs)
        )

    def test_export_bundle_cli_share_agents_keeps_bare_actor(self, tmp_path):
        """A --share-agents checkpoint must export the ONE shared actor, not
        the A-fold broadcast the eval path builds — the bundle stays small
        and the engine serves through the flattened shared branch."""
        from p2pmicrogrid_tpu.cli import main
        from p2pmicrogrid_tpu.models.ddpg import ddpg_params_init
        from p2pmicrogrid_tpu.train.checkpoint import (
            checkpoint_dir,
            save_checkpoint,
        )

        cfg = _cfg("ddpg", share_across_agents=True)
        ps = ddpg_params_init(cfg.ddpg, A, jax.random.PRNGKey(0))
        model_dir = str(tmp_path / "models")
        setting = f"{cfg.setting}-x2-shared"
        save_checkpoint(
            checkpoint_dir(model_dir, setting, "ddpg"), ps, episode=5
        )
        out_dir = str(tmp_path / "bundle")
        rc = main([
            "export-bundle", "--agents", str(A), "--implementation", "ddpg",
            "--scenarios", "2", "--shared", "--share-agents",
            "--model-dir", model_dir, "--out", out_dir,
        ])
        assert rc == 0
        manifest, params = load_policy_bundle(out_dir)
        assert manifest["model"]["share_across_agents"] is True
        assert params["Dense_0"]["kernel"].ndim == 2  # no [A] broadcast
        engine = PolicyEngine(bundle_dir=out_dir)
        obs = _obs(4)
        np.testing.assert_allclose(
            engine.act(obs), _reference_actions(cfg, ps, obs), rtol=1e-6
        )


class TestBundleSchema:
    def test_exported_bundle_validates(self, tmp_path):
        import importlib.util
        import os

        cfg = _cfg("tabular")
        export_policy_bundle(cfg, _trained_state(cfg), str(tmp_path / "b"))
        spec = importlib.util.spec_from_file_location(
            "check_artifacts_schema",
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "check_artifacts_schema.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        problems: list = []
        mod.check_bundle_dir(str(tmp_path / "b"), problems)
        assert problems == []
        # And a corrupted manifest is caught.
        m = json.loads((tmp_path / "b" / "manifest.json").read_text())
        del m["implementation"]
        m["kind"] = "something_else"
        (tmp_path / "b" / "manifest.json").write_text(json.dumps(m))
        problems = []
        mod.check_bundle_dir(str(tmp_path / "b"), problems)
        assert any("kind" in p for p in problems)
        assert any("implementation" in p for p in problems)
