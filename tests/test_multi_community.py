"""Multi-community (inter-community trading) tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.envs.multi_community import (
    inter_community_traded_fraction,
    train_multi_community,
)
from p2pmicrogrid_tpu.parallel import (
    make_scenario_traces,
    stack_scenario_arrays,
    train_scenarios_shared,
)
from p2pmicrogrid_tpu.train import init_policy_state, make_policy

C, A = 4, 3


# Whole module is compile-heavy (multi-community episode compiles).
pytestmark = pytest.mark.slow

class TestTradedFraction:
    def test_opposite_residuals_fully_match(self):
        # Two communities with exactly opposite residuals trade fully.
        p_grid = jnp.array([[600.0, 400.0], [-500.0, -500.0]])
        f = inter_community_traded_fraction(p_grid)
        np.testing.assert_allclose(np.asarray(f), [1.0, 1.0], atol=1e-6)

    def test_same_sign_residuals_no_trade(self):
        p_grid = jnp.array([[600.0, 400.0], [500.0, 500.0]])
        f = inter_community_traded_fraction(p_grid)
        np.testing.assert_allclose(np.asarray(f), [0.0, 0.0], atol=1e-6)

    def test_partial_match(self):
        # Surplus community covers only part of the deficit community.
        p_grid = jnp.array([[1000.0], [-250.0], [0.0]])
        f = inter_community_traded_fraction(p_grid)
        # Community 0 offers 500 to each of 1, 2; community 1 offers -125 to
        # each; matching community 0 <-> 1 clears min(500, 125) = 125.
        np.testing.assert_allclose(float(f[0]), 125.0 / 1000.0, atol=1e-6)
        np.testing.assert_allclose(float(f[1]), 125.0 / 250.0, atol=1e-6)
        assert float(f[2]) == 0.0

    def test_zero_residual_safe(self):
        p_grid = jnp.zeros((3, 2))
        f = inter_community_traded_fraction(p_grid)
        assert np.isfinite(np.asarray(f)).all()
        np.testing.assert_allclose(np.asarray(f), 0.0)


class TestConservativeSettlement:
    def test_repriced_energy_equals_matched_energy(self):
        """The trade-priced share of grid energy must equal the matched
        inter-community power exactly, even when some agents' grid power
        opposes their community's residual (ADVICE round 1)."""
        from p2pmicrogrid_tpu.envs.multi_community import (
            make_inter_community_settlement,
        )

        cfg = default_config(sim=SimConfig(n_agents=3))
        settle = make_inter_community_settlement(cfg)
        # Residuals r = [+800, -500]; with C=2 each community offers its full
        # residual to the other, so matched = [+500, -500], f = [0.625, 1.0].
        # Community 0 also has a counter-sign agent (-200) that must settle at
        # the plain tariff.
        p_grid = jnp.array([[700.0, 300.0, -200.0], [-100.0, -300.0, -100.0]])
        p_p2p = jnp.zeros_like(p_grid)
        buy = jnp.array([0.15, 0.15])
        inj = jnp.array([0.07, 0.07])
        trade = jnp.array([0.11, 0.11])

        cost = settle(p_grid, p_p2p, buy, inj, trade)
        # Plain-tariff settlement for comparison.
        tariff = jnp.where(p_grid >= 0.0, buy[:, None], inj[:, None])
        plain = p_grid * tariff * cfg.sim.slot_hours * 1e-3

        r = jnp.sum(p_grid, axis=-1)
        f = inter_community_traded_fraction(p_grid)
        matched = f * r
        # Savings per community = matched * (tariff_of_residual_sign - trade):
        # every re-priced watt belonged to a residual-sign agent.
        res_tariff = jnp.where(r >= 0.0, buy, inj)
        expected_delta = matched * (trade - res_tariff) * cfg.sim.slot_hours * 1e-3
        np.testing.assert_allclose(
            np.asarray(jnp.sum(cost - plain, axis=-1)),
            np.asarray(expected_delta),
            rtol=1e-5,
        )
        # And something actually matched in this fixture.
        assert float(jnp.abs(matched).sum()) > 0.0


class TestTraining:
    def setup_method(self):
        self.cfg = default_config(
            sim=SimConfig(n_agents=A, n_scenarios=C),
            train=TrainConfig(implementation="tabular"),
        )
        self.ratings = make_ratings(self.cfg, np.random.default_rng(42))
        traces = make_scenario_traces(self.cfg)
        self.arrays = stack_scenario_arrays(self.cfg, traces, self.ratings)
        self.policy = make_policy(self.cfg)
        self.ps = init_policy_state(self.cfg, jax.random.PRNGKey(1))

    def test_episode_runs_and_learns(self):
        ps2, _, rewards, _, _ = train_multi_community(
            self.cfg, self.policy, self.ps, self.arrays, self.ratings,
            jax.random.PRNGKey(0), n_episodes=1,
        )
        assert rewards.shape == (1, C)
        assert np.isfinite(rewards).all()
        assert float(jnp.abs(ps2.q_table - self.ps.q_table).max()) > 0.0

    def test_inter_trading_changes_costs_vs_isolated(self):
        """With inter-community trading the blended grid price is never worse
        than the tariff, so total reward must be >= the isolated-communities
        run (same seeds, same policy draws)."""
        _, _, r_inter, _, _ = train_multi_community(
            self.cfg, self.policy, self.ps, self.arrays, self.ratings,
            jax.random.PRNGKey(0), n_episodes=1,
        )
        _, _, r_iso, _, _ = train_scenarios_shared(
            self.cfg, self.policy, self.ps, self.arrays, self.ratings,
            jax.random.PRNGKey(0), n_episodes=1,
        )
        assert not np.allclose(r_inter, r_iso)
        assert (r_inter + 1e-5 >= r_iso).all()


class TestMultiCommunityEval:
    @pytest.mark.parametrize("impl", ["tabular", "ddpg"])
    def test_greedy_per_day_eval_shapes_and_trading(self, impl):
        """evaluate_multi_community: greedy per-day run of the shared learner
        (the reference's load_and_run, community.py:364-412, at config 5)."""
        from p2pmicrogrid_tpu.config import DDPGConfig
        from p2pmicrogrid_tpu.data import synthetic_traces, train_validation_test_split
        from p2pmicrogrid_tpu.envs.multi_community import evaluate_multi_community
        from p2pmicrogrid_tpu.parallel import init_shared_state

        cfg = default_config(
            sim=SimConfig(n_agents=A, n_scenarios=C),
            train=TrainConfig(implementation=impl),
            ddpg=DDPGConfig(
                buffer_size=16, batch_size=2, share_across_agents=True
            ),
        )
        ratings = make_ratings(cfg, np.random.default_rng(42))
        policy = make_policy(cfg)
        ps, _ = init_shared_state(cfg, jax.random.PRNGKey(0))
        _, _, test_traces = train_validation_test_split(synthetic_traces())

        days, outputs, day_arrays = evaluate_multi_community(
            cfg, policy, ps, test_traces, ratings, jax.random.PRNGKey(1)
        )
        D, T = len(days), 96
        assert outputs.cost.shape == (D, T, C, A)
        assert day_arrays.load_w.shape == (D, C, T, A)
        assert np.isfinite(np.asarray(outputs.cost)).all()
        # Redrawn profile scales differentiate the communities.
        assert not np.allclose(
            np.asarray(day_arrays.load_w[:, 0]),
            np.asarray(day_arrays.load_w[:, 1]),
        )

    def test_cli_multi_train_then_eval_persists_per_community(self, tmp_path):
        """VERDICT round 2 gap: `eval` after `multi` must produce per-community
        test_results rows."""
        import sqlite3

        from p2pmicrogrid_tpu.cli import main

        db = str(tmp_path / "r.db")
        common = [
            "--communities", "3", "--agents", "2",
            "--results-db", db, "--model-dir", str(tmp_path / "m"),
        ]
        assert main(["multi", *common, "--episodes", "2"]) == 0
        assert main(["eval", *common, "--test"]) == 0
        with sqlite3.connect(db) as conn:
            settings = {
                r[0]
                for r in conn.execute(
                    "SELECT DISTINCT setting FROM test_results"
                ).fetchall()
            }
        assert {
            "multi-3x2-rounds-1-c0",
            "multi-3x2-rounds-1-c1",
            "multi-3x2-rounds-1-c2",
        } <= settings
