"""Crash-safe training: atomic checkpoints, exact resume, rollback, harness.

The training-tier acceptance contract (ISSUE 8, mirroring the serve fleet's
tests/test_fleet.py):

* kill-at-episode-k + auto-resume == uninterrupted run, bit-exact
  (tabular + DQN, pipelined and sync);
* a corrupted newest checkpoint falls back to the previous verified step;
* an injected-NaN run rolls back to the last good checkpoint and converges;
* the supervisor relaunches crashed children with capped backoff;
* RESILIENCE captures and checkpoint manifests validate in check_all.
"""

import importlib.util
import json
import os
import sys

import jax
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import (
    DQNConfig,
    SimConfig,
    TrainConfig,
    default_config,
)
from p2pmicrogrid_tpu.data import synthetic_traces
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.train import (
    init_policy_state,
    make_policy,
    train_community,
)
from p2pmicrogrid_tpu.train.checkpoint import (
    CheckpointCorrupt,
    latest_checkpoint,
    load_manifest,
    restore_checkpoint,
    restore_resume_state,
    save_checkpoint,
    verify_checkpoint,
)
from p2pmicrogrid_tpu.train.faults import (
    SimulatedPreemption,
    TrainFaultEvent,
    TrainFaultInjector,
    TrainFaultPlan,
    corrupt_step_files,
    kill_plan,
    poison_pol_state,
)
from p2pmicrogrid_tpu.train.resilience import (
    DivergenceGuard,
    DivergenceTripped,
    GuardPolicy,
    RollbackExhausted,
    checkpoint_callback,
    prepare_resume,
    supervise,
    train_chunked_with_rollback,
    train_community_with_rollback,
)


def _cfg(impl="tabular", max_episodes=8):
    return default_config(
        sim=SimConfig(n_agents=2),
        train=TrainConfig(
            implementation=impl,
            max_episodes=max_episodes,
            episodes_per_jit_block=2,
            save_episodes=2,
            min_episodes_criterion=2,
        ),
        dqn=DQNConfig(buffer_size=32, warmup_passes=1),
    )


@pytest.fixture(scope="module")
def traces():
    return synthetic_traces(n_days=1, seed=0, start_day=11).normalized()


def _leaves(ps):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(ps)]


def _assert_trees_equal(a, b):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y)


# --- exact resume ------------------------------------------------------------


@pytest.mark.parametrize("impl", ["tabular", "dqn"])
@pytest.mark.parametrize("pipeline", [True, False])
def test_kill_resume_bit_exact(tmp_path, traces, impl, pipeline):
    """SIGKILL (simulated in-process) at a seeded episode + auto-resume
    produces bit-identical final params to the uninterrupted run."""
    cfg = _cfg(impl)
    ratings = make_ratings(cfg, np.random.default_rng(0))
    policy = make_policy(cfg)
    ps0 = init_policy_state(cfg, jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(2)
    ckpt_dir = str(tmp_path / "ckpt")

    # Uninterrupted reference. The checkpoint callback must be present so
    # the fused blocks chop at the same save boundaries as the crashed run.
    ref = train_community(
        cfg, policy, ps0, traces, ratings, key,
        checkpoint_cb=lambda ep, ps: None, pipeline=pipeline,
    )

    # Crashed run: kill before episode 4 (last checkpoint: episode 3).
    plan = TrainFaultPlan(
        seed=0, events=(TrainFaultEvent(kind="kill", episode=4),)
    )
    injector = TrainFaultInjector(plan, kill_mode="raise")
    with pytest.raises(SimulatedPreemption):
        train_community(
            cfg, policy, ps0, traces, ratings, key,
            checkpoint_cb=checkpoint_callback(ckpt_dir, cfg),
            pipeline=pipeline, fault_hook=injector.on_block_start,
        )
    assert injector.history == [("kill", 4, 0)]

    # Auto-resume: the restored RNG chain + warmup skip replay the
    # surviving episodes exactly.
    template = init_policy_state(cfg, jax.random.PRNGKey(1))
    resume = prepare_resume(cfg, ckpt_dir, template, key)
    assert resume.resumed and resume.exact
    assert resume.episode == 3
    assert resume.cfg.train.starting_episodes == 4
    res = train_community(
        resume.cfg, policy, resume.pol_state, traces, ratings, resume.key,
        checkpoint_cb=checkpoint_callback(ckpt_dir, resume.cfg),
        pipeline=pipeline, warmup=resume.warmup,
    )
    _assert_trees_equal(ref.pol_state, res.pol_state)


def test_final_checkpoint_carries_rng_key(tmp_path, traces):
    """A completed run's final save (rng_key=result.rng_key) resumes as a
    verified no-op: episode at max, exact key present."""
    cfg = _cfg()
    ratings = make_ratings(cfg, np.random.default_rng(0))
    policy = make_policy(cfg)
    ps0 = init_policy_state(cfg, jax.random.PRNGKey(1))
    ckpt_dir = str(tmp_path / "ckpt")
    res = train_community(
        cfg, policy, ps0, traces, ratings, jax.random.PRNGKey(2),
        checkpoint_cb=checkpoint_callback(ckpt_dir, cfg),
    )
    save_checkpoint(
        ckpt_dir, res.pol_state, cfg.train.max_episodes - 1,
        rng_key=res.rng_key, cfg=cfg,
    )
    st = restore_resume_state(ckpt_dir, ps0)
    assert st.episode == cfg.train.max_episodes - 1
    assert st.rng_key is not None
    np.testing.assert_array_equal(st.rng_key, np.asarray(res.rng_key))
    manifest = load_manifest(st.step_path)
    assert manifest["config_hash"]


def test_legacy_checkpoint_resumes_rekeyed(tmp_path):
    """A checkpoint without an RNG key (pre-rewrite / scenario path) resumes
    through the historical fold_in schedule, flagged non-exact."""
    cfg = _cfg()
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    ckpt_dir = str(tmp_path / "ckpt")
    save_checkpoint(ckpt_dir, ps, episode=3)
    plan = prepare_resume(cfg, ckpt_dir, ps, jax.random.PRNGKey(2))
    assert plan.resumed and not plan.exact and plan.warmup
    assert plan.cfg.train.starting_episodes == 4


def test_prepare_resume_without_checkpoint_starts_fresh(tmp_path):
    cfg = _cfg()
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    plan = prepare_resume(cfg, str(tmp_path / "none"), ps, jax.random.PRNGKey(2))
    assert not plan.resumed and plan.warmup
    assert plan.cfg.train.starting_episodes == 0


# --- atomic checkpoints ------------------------------------------------------


def test_corrupt_newest_falls_back_to_verified(tmp_path):
    cfg = _cfg()
    ps3 = init_policy_state(cfg, jax.random.PRNGKey(3))
    ps5 = init_policy_state(cfg, jax.random.PRNGKey(5))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, ps3, episode=3, rng_key=jax.random.PRNGKey(3))
    step5 = save_checkpoint(path, ps5, episode=5, rng_key=jax.random.PRNGKey(5))
    assert latest_checkpoint(path).endswith("ep_5")

    assert corrupt_step_files(step5) is not None
    with pytest.warns(UserWarning, match="corrupt"):
        assert latest_checkpoint(path).endswith("ep_3")
    # Unverified listing still names the newest (cheap path).
    assert latest_checkpoint(path, verify=False).endswith("ep_5")

    template = init_policy_state(cfg, jax.random.PRNGKey(99))
    with pytest.warns(UserWarning, match="corrupt"):
        restored, episode = restore_checkpoint(path, template)
    assert episode == 3
    _assert_trees_equal(restored, ps3)


def test_all_steps_corrupt_raises(tmp_path):
    cfg = _cfg()
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    path = str(tmp_path / "ckpt")
    step = save_checkpoint(path, ps, episode=1)
    corrupt_step_files(step)
    with pytest.warns(UserWarning, match="corrupt"):
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(path, ps)


def test_malformed_step_dir_skipped_with_warning(tmp_path):
    cfg = _cfg()
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, ps, episode=2)
    os.makedirs(os.path.join(path, "ep_banana"))
    with pytest.warns(UserWarning, match="malformed"):
        assert latest_checkpoint(path).endswith("ep_2")


def test_prune_waits_for_readback_verification(tmp_path, monkeypatch):
    """A failing write NEVER strands the run: the previous step survives a
    save whose read-back verification fails (the pre-rewrite hazard was
    prune-before-verify)."""
    import p2pmicrogrid_tpu.train.checkpoint as ckpt_mod

    cfg = _cfg()
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, ps, episode=1)

    def broken(tmp_path_, digest_):
        raise CheckpointCorrupt("simulated torn write")

    monkeypatch.setattr(ckpt_mod, "_verify_readback", broken)
    with pytest.raises(CheckpointCorrupt, match="torn write"):
        save_checkpoint(path, ps, episode=3)
    monkeypatch.undo()
    assert latest_checkpoint(path).endswith("ep_1")
    restored, episode = restore_checkpoint(path, ps)
    assert episode == 1
    # The next good save reclaims the stale temp dir.
    save_checkpoint(path, ps, episode=3)
    assert not [d for d in os.listdir(path) if d.startswith("_tmp_ep_")]
    assert latest_checkpoint(path).endswith("ep_3")


def test_prune_keeps_fallback_and_removes_stale_higher(tmp_path):
    cfg = _cfg()
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    path = str(tmp_path / "ckpt")
    for ep in (1, 3, 5):
        save_checkpoint(path, ps, episode=ep)
    names = sorted(d for d in os.listdir(path) if d.startswith("ep_"))
    assert names == ["ep_3", "ep_5"]  # keep_last=2: newest + one fallback
    # A lower-episode save (fresh shorter run) prunes the stale higher steps
    # so they can never shadow it.
    save_checkpoint(path, ps, episode=2)
    names = sorted(d for d in os.listdir(path) if d.startswith("ep_"))
    assert names == ["ep_2"]


def test_verify_checkpoint_detects_manifest_payload_skew(tmp_path):
    cfg = _cfg()
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    path = str(tmp_path / "ckpt")
    step = save_checkpoint(path, ps, episode=4)
    assert verify_checkpoint(step)["episode"] == 4
    m = load_manifest(step)
    m["digest"] = "sha256:" + "0" * 64
    with open(os.path.join(step, "p2p_manifest.json"), "w") as f:
        json.dump(m, f)
    with pytest.raises(CheckpointCorrupt, match="digest mismatch"):
        verify_checkpoint(step)


def test_health_state_rides_checkpoint_extra(tmp_path):
    from p2pmicrogrid_tpu.train.health import HealthMonitor

    cfg = _cfg()
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    monitor = HealthMonitor(slots=96, warn_stream=open(os.devnull, "w"))
    monitor.update(0, 3000.0, -800.0)      # untrained
    monitor.update(10, -50.0, -1500.0)     # basin entry
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, ps, episode=10, extra={"health": monitor.to_dict()})
    st = restore_resume_state(path, ps)
    restored = HealthMonitor.from_dict(st.extra["health"])
    assert restored.in_basin
    assert restored.basin_entries == monitor.basin_entries
    assert restored.initial_cost == monitor.initial_cost
    assert [p.status for p in restored.points] == [
        p.status for p in monitor.points
    ]
    assert len(restored.points) == 2


# --- divergence rollback -----------------------------------------------------


def test_rollback_on_injected_nan(tmp_path, traces):
    """poison-NaN at a seeded episode: the guard trips on the in-program
    nonfinite counters, training rolls back to the last GOOD checkpoint and
    converges to a finite final state."""
    from p2pmicrogrid_tpu.telemetry import MemorySink, Telemetry

    cfg = _cfg()
    ratings = make_ratings(cfg, np.random.default_rng(0))
    ps0 = init_policy_state(cfg, jax.random.PRNGKey(1))
    ckpt_dir = str(tmp_path / "ckpt")
    plan = TrainFaultPlan(
        seed=0, events=(TrainFaultEvent(kind="poison_nan", episode=4),)
    )
    injector = TrainFaultInjector(plan, kill_mode="raise")
    sink = MemorySink()
    tel = Telemetry(run_id="rollback-test", sinks=[sink])
    result, rollbacks = train_community_with_rollback(
        cfg, ps0, traces, ratings, jax.random.PRNGKey(2), ckpt_dir,
        guard_policy=GuardPolicy(max_rollbacks=2),
        telemetry=tel, fault_injector=injector,
    )
    tel.close()
    assert len(rollbacks) == 1
    assert rollbacks[0].restored_episode == 3
    assert rollbacks[0].tripped_episode >= 4
    assert rollbacks[0].lr_scale == 0.5
    for leaf in _leaves(result.pol_state):
        if np.issubdtype(leaf.dtype, np.floating):
            assert np.isfinite(leaf).all()
    assert tel.counters["train.rollback"] == 1
    kinds = [r.get("kind") for r in sink.records]
    assert "divergence" in kinds and "rollback" in kinds


def test_rollback_exhausted_raises(tmp_path, traces):
    """A fault that re-poisons every attempt exhausts the budget loudly."""

    class AlwaysPoison:
        def on_block_start(self, ep, pol_state=None):
            if ep >= 4 and pol_state is not None:
                return poison_pol_state(pol_state)
            return None

        def on_checkpoint_saved(self, ep, step):
            pass

        def on_callback(self, ep):
            pass

    cfg = _cfg()
    ratings = make_ratings(cfg, np.random.default_rng(0))
    ps0 = init_policy_state(cfg, jax.random.PRNGKey(1))
    with pytest.raises(RollbackExhausted):
        train_community_with_rollback(
            cfg, ps0, traces, ratings, jax.random.PRNGKey(2),
            str(tmp_path / "ckpt"),
            guard_policy=GuardPolicy(max_rollbacks=2),
            fault_injector=AlwaysPoison(),
        )


def test_guard_trips_and_is_single_shot():
    guard = DivergenceGuard(GuardPolicy())
    guard.observe_counters(3, {"nonfinite_q": 0, "nonfinite_loss": 0})
    with pytest.raises(DivergenceTripped) as exc:
        guard.observe_counters(5, {"nonfinite_q": 7, "nonfinite_loss": 0})
    assert exc.value.episode == 5
    # Spent: further observations are no-ops (the rollback driver builds a
    # fresh guard per attempt).
    guard.observe_counters(7, {"nonfinite_q": 9})
    guard.observe_health(7, "basin")


def test_guard_basin_verdict():
    guard = DivergenceGuard(GuardPolicy(trip_on_basin=True))
    guard.observe_health(10, "healthy")
    guard.observe_health(20, "slide")
    with pytest.raises(DivergenceTripped, match="basin"):
        guard.observe_health(30, "basin")
    # Default policy: basin is the health monitor's business, not a trip.
    DivergenceGuard(GuardPolicy()).observe_health(30, "basin")


# --- fault plans -------------------------------------------------------------


def test_fault_plan_json_roundtrip():
    plan = TrainFaultPlan(
        seed=7,
        events=(
            TrainFaultEvent(kind="kill", episode=5, attempt=0),
            TrainFaultEvent(kind="corrupt_checkpoint", episode=3, attempt=1),
            TrainFaultEvent(kind="stall_callback", episode=2, stall_s=0.01),
            TrainFaultEvent(kind="poison_nan", episode=4, attempt=None),
        ),
    )
    assert TrainFaultPlan.from_json(plan.to_json()) == plan
    with pytest.raises(ValueError):
        TrainFaultPlan.from_json(json.dumps({"kind": "fault_plan", "seed": 1}))
    with pytest.raises(ValueError):
        TrainFaultEvent(kind="meteor", episode=1)


def test_kill_plan_deterministic_and_attempt_scoped():
    a = kill_plan(seed=3, n_episodes=100, n_kills=3)
    b = kill_plan(seed=3, n_episodes=100, n_kills=3)
    assert a == b
    assert [e.attempt for e in a.events] == [0, 1, 2]
    assert all(1 <= e.episode < 100 for e in a.events)
    assert kill_plan(seed=4, n_episodes=100).events != a.events[:1]
    # Attempt scoping: attempt-1's injector ignores the attempt-0 kill.
    scoped = TrainFaultPlan(
        seed=0,
        events=(
            TrainFaultEvent(kind="kill", episode=5, attempt=0),
            TrainFaultEvent(kind="kill", episode=50, attempt=1),
        ),
    )
    inj = TrainFaultInjector(scoped, attempt=1, kill_mode="raise")
    inj.on_block_start(10)  # past the attempt-0 kill: no fire
    assert inj.history == []
    with pytest.raises(SimulatedPreemption):
        inj.on_block_start(50)


def test_stall_callback_fires_once():
    naps = []
    plan = TrainFaultPlan(
        seed=0, events=(TrainFaultEvent(kind="stall_callback", episode=2, stall_s=0.5),)
    )
    inj = TrainFaultInjector(plan, sleep=naps.append)
    inj.on_callback(1)
    inj.on_callback(2)
    inj.on_callback(3)
    assert naps == [0.5]


# --- supervisor --------------------------------------------------------------


_CRASHY_CHILD = """
import os, sys
attempt = int(os.environ["P2P_TRAIN_ATTEMPT"])
if attempt < 2:
    os.kill(os.getpid(), 9)
print('{"metric": "train_rollback", "value": 1, "unit": "rollback", "vs_baseline": 0.0}')
"""


def test_supervise_restarts_until_success():
    rows = []
    result = supervise(
        [sys.executable, "-c", _CRASHY_CHILD],
        max_restarts=4, backoff_s=0.01, backoff_cap_s=0.02,
        resume_flag=None, emit=rows.append,
        passthrough=open(os.devnull, "w"),
    )
    assert result.succeeded
    assert len(result.attempts) == 3
    assert result.kills == 2 and result.resumes == 2
    assert result.rollbacks == 1  # scanned from child stdout
    assert [r["exit_code"] for r in rows] == [-9, -9, 0]
    assert rows[0]["signal"] == 9 and rows[2]["signal"] == 0


def test_supervise_appends_resume_flag():
    child = (
        "import sys; sys.exit(0 if '--resume' in sys.argv else 7)"
    )
    result = supervise(
        [sys.executable, "-c", child],
        max_restarts=2, backoff_s=0.01, backoff_cap_s=0.02,
        passthrough=open(os.devnull, "w"),
    )
    assert result.succeeded and len(result.attempts) == 2
    assert result.attempts[0]["exit_code"] == 7


def test_supervise_gives_up_after_cap():
    result = supervise(
        [sys.executable, "-c", "import sys; sys.exit(3)"],
        max_restarts=2, backoff_s=0.01, backoff_cap_s=0.02,
        resume_flag=None, passthrough=open(os.devnull, "w"),
    )
    assert not result.succeeded
    assert result.exit_code == 3
    assert len(result.attempts) == 3  # initial + 2 restarts


# --- schema checks -----------------------------------------------------------


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_artifacts_schema",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "check_artifacts_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


GOOD_HEADLINE = {
    "metric": "train_supervised", "value": 2, "unit": "attempts",
    "vs_baseline": 0.0, "kills": 1, "resumes": 1, "rollbacks": 0,
    "final_episode": 7, "bit_exact": True,
}


def test_resilience_jsonl_schema(tmp_path):
    checker = _load_checker()
    art = tmp_path / "artifacts"
    art.mkdir()
    good = art / "RESILIENCE_r98.jsonl"
    rows = [
        {"metric": "supervise_attempt", "value": 0, "unit": "attempt",
         "vs_baseline": 0.0, "exit_code": -9},
        GOOD_HEADLINE,
        {"metric": "train_rollback_total", "value": 1, "unit": "rollbacks",
         "vs_baseline": 0.0, "converged": True},
    ]
    good.write_text("".join(json.dumps(r) + "\n" for r in rows))
    problems = []
    checker.check_resilience_jsonl(str(good), problems)
    assert problems == []

    bad = art / "RESILIENCE_r99.jsonl"
    bad_headline = dict(GOOD_HEADLINE)
    del bad_headline["bit_exact"]
    bad_headline["kills"] = "one"
    bad.write_text(json.dumps(bad_headline) + "\n")
    problems = []
    checker.check_resilience_jsonl(str(bad), problems)
    assert any("bit_exact" in p for p in problems)
    assert any("kills" in p for p in problems)
    # check_all picks RESILIENCE files up from an artifact root.
    all_problems = checker.check_all(str(tmp_path))
    assert any("RESILIENCE_r99" in p for p in all_problems)
    assert not any("RESILIENCE_r98" in p for p in all_problems)


def test_checkpoint_manifest_schema(tmp_path):
    checker = _load_checker()
    cfg = _cfg()
    ps = init_policy_state(cfg, jax.random.PRNGKey(0))
    from p2pmicrogrid_tpu.train.checkpoint import checkpoint_dir

    ckpt_dir = checkpoint_dir(str(tmp_path / "models"), cfg.setting, "tabular")
    step = save_checkpoint(ckpt_dir, ps, episode=3, cfg=cfg)
    problems = checker.check_all(str(tmp_path))
    assert not [p for p in problems if "p2p_manifest" in p]

    m = load_manifest(step)
    del m["digest"]
    m["tree"] = {}
    with open(os.path.join(step, "p2p_manifest.json"), "w") as f:
        json.dump(m, f)
    problems = checker.check_all(str(tmp_path))
    assert any("digest" in p for p in problems)
    assert any("tree" in p for p in problems)


# --- warehouse ---------------------------------------------------------------


def test_rollback_view_joins_on_config_hash(tmp_path):
    from p2pmicrogrid_tpu.data import ResultsStore
    from p2pmicrogrid_tpu.telemetry import SqliteSink, Telemetry
    from p2pmicrogrid_tpu.telemetry.registry import run_manifest

    cfg = _cfg()
    db = str(tmp_path / "results.db")
    tel = Telemetry(
        run_id="resilience-run",
        sinks=[SqliteSink(db)],
        manifest=run_manifest(cfg),
    )
    tel.counter("train.divergence")
    tel.counter("train.rollback")
    tel.event(
        "rollback", attempt=1, episode=5, restored_episode=3,
        lr_scale=0.5, reason="nonfinite_q=7 nonfinite_loss=0",
    )
    tel.close()
    store = ResultsStore(db)
    rows = store.query_rollback_view()
    assert len(rows) == 1
    row = rows[0]
    assert row["rollbacks"] == 1
    assert row["divergence_trips"] == 1
    assert row["rollback_events"] == 1
    assert row["last_rollback_episode"] == 5
    assert row["last_restored_episode"] == 3
    assert row["config_hash"]


# --- CLI (in-process; the real-SIGKILL end-to-end run is marked slow) --------


def test_cli_resume_noop_verifies_integrity(tmp_path, traces, capsys, monkeypatch):
    """`train --resume` with the checkpoint at --episodes verifies the final
    checkpoint's integrity and reports the no-op."""
    from p2pmicrogrid_tpu import cli

    monkeypatch.setenv("P2P_TELEMETRY", "0")
    monkeypatch.chdir(tmp_path)
    argv = [
        "train", "--agents", "2", "--episodes", "4", "--seed", "3",
        "--model-dir", str(tmp_path / "models"), "--no-pipeline",
    ]
    assert cli.main(argv) == 0
    capsys.readouterr()
    assert cli.main(argv + ["--resume"]) == 0
    out = capsys.readouterr().out
    assert "nothing to do" in out and "integrity verified" in out


@pytest.mark.slow
def test_cli_supervised_sigkill_bit_exact(tmp_path):
    """End-to-end acceptance: real SIGKILL mid-training under
    `train --supervise`, auto-resume, bit-exact vs uninterrupted."""
    import subprocess

    out_path = tmp_path / "RESILIENCE_test.jsonl"
    argv = [
        sys.executable, "-m", "p2pmicrogrid_tpu", "train",
        "--agents", "2", "--episodes", "8", "--seed", "3",
        "--model-dir", str(tmp_path / "models"),
        "--supervise", "--fault-seed", "0", "--fault-kills", "1",
        "--verify-uninterrupted", "--resilience-out", str(out_path),
        "--max-restarts", "3",
    ]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["P2P_TELEMETRY"] = "0"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(argv, env=env, cwd=str(tmp_path),
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = [json.loads(l) for l in out_path.read_text().splitlines()]
    headline = [r for r in rows if r.get("metric") == "train_supervised"][-1]
    assert headline["bit_exact"] is True
    assert headline["kills"] >= 1 and headline["resumes"] >= 1
    checker = _load_checker()
    problems = []
    checker.check_resilience_jsonl(str(out_path), problems)
    assert problems == []


# -- chunked rollback (ISSUE 9 satellite: the chunked half of the driver) ------


def _chunked_cfg(max_episodes=12):
    return default_config(
        sim=SimConfig(n_agents=2, n_scenarios=4),
        train=TrainConfig(
            implementation="tabular", seed=0,
            max_episodes=max_episodes, save_episodes=4,
        ),
    )


def test_chunked_rollback_restores_and_reenters(tmp_path):
    """A divergence trip at a block-boundary eval restores the newest
    verified checkpoint, drops the lr, branches the chunk key stream and
    re-enters — the chunked mirror of train_community_with_rollback."""
    from p2pmicrogrid_tpu.parallel import init_shared_pol_state
    from p2pmicrogrid_tpu.telemetry import MemorySink, Telemetry

    cfg = _chunked_cfg()
    ratings = make_ratings(cfg, np.random.default_rng(0))
    key = jax.random.PRNGKey(0)
    ps0 = init_shared_pol_state(cfg, key)
    trips = {"n": 0}

    def health_cb(point):
        # One injected divergence at the episode-8 eval, first attempt
        # only — the same exception path a guard trip takes (do_eval
        # raises through train_chunked_with_health).
        if point.episode >= 8 and trips["n"] == 0:
            trips["n"] += 1
            raise DivergenceTripped(point.episode, "injected test trip")

    sink = MemorySink()
    tel = Telemetry(run_id="chunked-rollback-test", sinks=[sink])
    result, rollbacks = train_chunked_with_rollback(
        cfg, ps0, ratings, key, str(tmp_path / "ckpt"),
        n_episodes=12, n_chunks=2, eval_every=4,
        guard_policy=GuardPolicy(max_rollbacks=2, lr_drop=0.5),
        telemetry=tel, health_cb=health_cb,
    )
    tel.close()
    pol_state, rewards, losses, seconds, monitor = result
    assert len(rollbacks) == 1
    # Saved at episodes 3 and 7 before the trip at 8: restore ep 7.
    assert rollbacks[0].restored_episode == 7
    assert rollbacks[0].tripped_episode == 8
    assert rollbacks[0].lr_scale == 0.5
    assert np.isfinite(rewards).all()
    assert tel.counters["train.rollback"] == 1
    assert "rollback" in [r.get("kind") for r in sink.records]


def test_chunked_rollback_exhausts_budget(tmp_path):
    """A trip that re-fires every attempt raises RollbackExhausted."""
    from p2pmicrogrid_tpu.parallel import init_shared_pol_state

    cfg = _chunked_cfg()
    ratings = make_ratings(cfg, np.random.default_rng(0))
    key = jax.random.PRNGKey(0)
    ps0 = init_shared_pol_state(cfg, key)

    def health_cb(point):
        if point.episode >= 8:
            raise DivergenceTripped(point.episode, "persistent trip")

    with pytest.raises(RollbackExhausted):
        train_chunked_with_rollback(
            cfg, ps0, ratings, key, str(tmp_path / "ckpt"),
            n_episodes=12, n_chunks=2, eval_every=4,
            guard_policy=GuardPolicy(max_rollbacks=1),
            health_cb=health_cb,
        )


def test_chunked_rollback_cli_requires_health(tmp_path):
    """--max-rollbacks on the scenario path without the chunked health
    surface is refused loudly, not silently ignored."""
    from p2pmicrogrid_tpu import cli

    with pytest.raises(SystemExit) as exc:
        cli.main([
            "train", "--implementation", "tabular", "--agents", "2",
            "--scenarios", "4", "--shared", "--chunks", "2",
            "--episodes", "8", "--health-every", "0",
            "--max-rollbacks", "2",
            "--model-dir", str(tmp_path / "models"),
        ])
    assert "--health-every" in str(exc.value)
