"""Pallas kernel parity tests (interpreter mode on the CPU mesh).

The fused negotiation/market kernels (ops/pallas_market.py) must match the
jnp reference path (ops/market.py) bit-for-bit modulo float reassociation,
including the sign-matching and equal-split edge cases; and a full
shared-scenario episode with use_pallas=True must match use_pallas=False.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pmicrogrid_tpu.config import SimConfig, TrainConfig, default_config
from p2pmicrogrid_tpu.envs import make_ratings
from p2pmicrogrid_tpu.ops.market import clear_market, divide_power, zero_diagonal
from p2pmicrogrid_tpu.ops.pallas_market import (
    clear_market_fused,
    divide_power_fused,
    divide_power_fused_with_mean,
    prep_mean,
)
from p2pmicrogrid_tpu.parallel import (
    make_scenario_traces,
    stack_scenario_arrays,
    train_scenarios_shared,
)
from p2pmicrogrid_tpu.train import init_policy_state, make_policy

S, A = 4, 6


# Whole module is compile-heavy (episode-level Pallas/bf16 parity runs).
pytestmark = pytest.mark.slow

@pytest.fixture(scope="module")
def p2p():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((S, A, A)).astype(np.float32) * 1e3
    # Edge cases: exact zeros (sign 0) and a same-sign scenario where no
    # counterparty matches (equal-split branch).
    x[0, 0, :] = 0.0
    x[1] = np.abs(x[1])
    return jnp.asarray(x)


@pytest.fixture(scope="module")
def out_power():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((S, A)).astype(np.float32) * 1e3
    x[2, 0] = 0.0
    return jnp.asarray(x)


def test_prep_mean_matches_reference(p2p):
    p2p_zd = jax.vmap(zero_diagonal)(p2p)
    powers = -jnp.swapaxes(p2p_zd, -1, -2)
    ref = jnp.mean(powers, axis=-1)
    got = prep_mean(p2p)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-3)


def test_divide_power_matches_reference(p2p, out_power):
    p2p_zd = jax.vmap(zero_diagonal)(p2p)
    powers = -jnp.swapaxes(p2p_zd, -1, -2)
    ref = jax.vmap(divide_power)(out_power, powers)
    got = divide_power_fused(p2p, out_power)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-3)


def test_divide_rank1_matches_materialized(out_power):
    """divide_rank1_fused(v, out) == divide_power_fused_with_mean(rank1(v), out)
    where rank1(v)[s, i, j] = v[s, i] / A (the first round's exact output)."""
    from p2pmicrogrid_tpu.ops.pallas_market import divide_rank1_fused

    rng = np.random.default_rng(3)
    prev = jnp.asarray(rng.standard_normal((S, A)).astype(np.float32) * 1e3)
    rank1 = jnp.broadcast_to((prev / A)[:, :, None], (S, A, A))
    new_ref, mean_ref = divide_power_fused_with_mean(rank1, out_power)
    new, mean = divide_rank1_fused(prev, out_power)
    np.testing.assert_allclose(np.asarray(new), np.asarray(new_ref), rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(mean_ref), rtol=1e-5, atol=1e-3)


def test_divide_with_mean_matches_composition(p2p, out_power):
    """divide_power_fused_with_mean == (divide_power_fused, prep_mean of it)."""
    new_ref = divide_power_fused(p2p, out_power)
    mean_ref = prep_mean(new_ref)
    new, mean = divide_power_fused_with_mean(p2p, out_power)
    np.testing.assert_allclose(np.asarray(new), np.asarray(new_ref), rtol=1e-5, atol=1e-3)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(mean_ref), rtol=1e-5, atol=1e-3)


def test_clear_market_matches_reference(p2p):
    ref_grid, ref_peer = clear_market(p2p)
    got_grid, got_peer = clear_market_fused(p2p)
    np.testing.assert_allclose(np.asarray(got_grid), np.asarray(ref_grid), rtol=1e-5, atol=1e-2)
    np.testing.assert_allclose(np.asarray(got_peer), np.asarray(ref_peer), rtol=1e-5, atol=1e-2)


@pytest.mark.parametrize("rounds", [0, 1, 2])
def test_shared_episode_pallas_parity(rounds):
    """Full shared-tabular episode: use_pallas=True == use_pallas=False, for
    every structurally distinct round count of the specialized Pallas loop
    (0 = rank-1 broadcast fallback, 1 = rank-1 kernel, 2 = full fused kernel
    on the later round)."""
    results = {}
    for use_pallas in (False, True):
        cfg = default_config(
            sim=SimConfig(
                n_agents=3, n_scenarios=S, use_pallas=use_pallas, rounds=rounds
            ),
            train=TrainConfig(implementation="tabular"),
        )
        ratings = make_ratings(cfg, np.random.default_rng(42))
        traces = make_scenario_traces(cfg)
        arrays = stack_scenario_arrays(cfg, traces, ratings)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        ps = ps._replace(
            q_table=jax.random.normal(jax.random.PRNGKey(5), ps.q_table.shape)
        )
        ps2, _, rewards, _, _ = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(0), n_episodes=1
        )
        results[use_pallas] = (np.asarray(rewards), np.asarray(ps2.q_table))

    np.testing.assert_allclose(results[True][0], results[False][0], rtol=1e-4)
    np.testing.assert_allclose(results[True][1], results[False][1], rtol=1e-4, atol=1e-7)


def test_bf16_market_storage_close_to_f32():
    """market_dtype='bfloat16' compresses only the carried proposal matrix
    (compute stays f32 in VMEM): episode rewards must track the f32 path to
    bf16 precision (~0.5% on Watt-scale proposals)."""
    rewards = {}
    for mdt in ("float32", "bfloat16"):
        cfg = default_config(
            sim=SimConfig(
                n_agents=3, n_scenarios=S, use_pallas=True, market_dtype=mdt
            ),
            train=TrainConfig(implementation="tabular"),
        )
        ratings = make_ratings(cfg, np.random.default_rng(42))
        traces = make_scenario_traces(cfg)
        arrays = stack_scenario_arrays(cfg, traces, ratings)
        policy = make_policy(cfg)
        ps = init_policy_state(cfg, jax.random.PRNGKey(1))
        _, _, r, _, _ = train_scenarios_shared(
            cfg, policy, ps, arrays, ratings, jax.random.PRNGKey(0), n_episodes=1
        )
        rewards[mdt] = np.asarray(r)
    np.testing.assert_allclose(
        rewards["bfloat16"], rewards["float32"], rtol=0.02, atol=0.5
    )


def test_resolve_market_dtype_auto():
    """market_dtype='auto' (the default): bfloat16 exactly on the Pallas path
    at >= MARKET_BF16_MIN_AGENTS agents, float32 everywhere else; explicit
    choices pass through."""
    from p2pmicrogrid_tpu.config import SimConfig, default_config
    from p2pmicrogrid_tpu.envs.community import (
        MARKET_BF16_MIN_AGENTS,
        resolve_market_dtype,
    )

    big = default_config(
        sim=SimConfig(n_agents=MARKET_BF16_MIN_AGENTS, use_pallas=True)
    )
    assert resolve_market_dtype(big) == "bfloat16"
    small = default_config(sim=SimConfig(n_agents=8, use_pallas=True))
    assert resolve_market_dtype(small) == "float32"
    off = default_config(
        sim=SimConfig(n_agents=MARKET_BF16_MIN_AGENTS, use_pallas=False)
    )
    assert resolve_market_dtype(off) == "float32"
    explicit = default_config(
        sim=SimConfig(n_agents=2, use_pallas=True, market_dtype="bfloat16")
    )
    assert resolve_market_dtype(explicit) == "bfloat16"


def test_merged_min_sums_pallas_matches_inline():
    """The measured-negative factored-market kernel (pallas_factored.py,
    P2P_FACTORED_PALLAS=1) must still be CORRECT: row/col sums match the
    shipped inline computation (interpret mode on CPU)."""
    from p2pmicrogrid_tpu.ops.pallas_factored import merged_min_sums_pallas

    k = jax.random.PRNGKey(0)
    S, A = 3, 50
    mk = lambda i: jax.random.uniform(jax.random.fold_in(k, i), (S, A))
    alpha, wplus, wminus, gamma = mk(0), mk(1), mk(2), mk(3)
    pb = (mk(4) > 0.5).astype(jnp.float32)
    ps = (mk(5) > 0.5).astype(jnp.float32)
    lhs = jnp.where(
        pb[..., :, None] > 0,
        alpha[..., :, None] * wplus[..., None, :],
        alpha[..., :, None],
    )
    rhs = jnp.where(
        ps[..., None, :] > 0,
        wminus[..., :, None] * gamma[..., None, :],
        gamma[..., None, :],
    )
    m = jnp.minimum(lhs, rhs)
    row, col = merged_min_sums_pallas(alpha, wplus, wminus, gamma, pb, ps,
                                      i_tile=16)
    np.testing.assert_allclose(np.asarray(row), np.asarray(jnp.sum(m, -1)),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(col), np.asarray(jnp.sum(m, -2)),
                               rtol=1e-6)
